"""State-sync chaos scenarios: snapshot-join, snapshot-tamper,
snapshot-torn-tail.

The adversary models follow the fast-sync catalogue's deterministic-
finality framing: a snapshot manifest is a finality claim about app
state, so the tamper scenario replays the PoTE stale/forged-proof
attack (arXiv:2512.09409) against the snapshot offer path — a forged
manifest with a lying app_hash, and a peer serving corrupted chunks
under an honest manifest.  The join scenario is the ACE-style rejoin
(arXiv:2603.10242): a node whose disk is gone recovers from a recent
snapshot plus a short verified tail instead of replaying the chain
from genesis.
"""

from __future__ import annotations

import dataclasses
import os
import shutil
import tempfile
import time

from tendermint_tpu.abci.app import create_app
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.p2p.switch import connect_switches, make_switch
from tendermint_tpu.proxy import ClientCreator
from tendermint_tpu.scenarios import fixtures, harness
from tendermint_tpu.scenarios import invariants as inv
from tendermint_tpu.scenarios.engine import register
from tendermint_tpu.state import execution
from tendermint_tpu.state.state import get_state
from tendermint_tpu.statesync.restore import (StateSyncer, StoreSource,
                                              verify_manifest_app_hash)
from tendermint_tpu.statesync.snapshot import (MANIFEST_NAME,
                                               SnapshotManifest,
                                               SnapshotStore)
from tendermint_tpu.utils import fail
from tendermint_tpu.utils.db import MemDB
from tendermint_tpu.utils.metrics import REGISTRY


def _apply_chain(state, conns, store, chain, on_applied=None):
    """Apply every block of `chain` into `state`/`store`; `on_applied`
    (height, state) fires after each block lands — the hook snapshot
    creation and state capture ride on."""
    for block, ps, seen in chain:
        store.save_block(block, ps, seen)
        execution.apply_block(state, None, conns.consensus, block,
                              ps.header, execution.MockMempool(),
                              check_last_commit=False)
        if on_applied is not None:
            on_applied(block.height, state)


def _snapshotting_source(chain_id, chain, gen, snap_store, interval,
                         capture_at=()):
    """A served chain whose app state is snapshotted every `interval`
    blocks during the apply (the source-side half of the state-sync
    protocol).  Returns (switch, state, store, app, captured) where
    `captured[h]` is (state.encode(), app_hash) at height h — the
    byte-exact parity reference for restores."""
    from tendermint_tpu.blockchain.reactor import BlockchainReactor
    state = get_state(MemDB(), gen)
    app = create_app("kvstore")
    conns = ClientCreator(app).new_app_conns()
    store = BlockStore(MemDB())
    captured: dict[int, tuple[bytes, bytes]] = {}

    def hook(height, st):
        if height % interval == 0:
            snap_store.create(st, app.snapshot_state())
        if height in capture_at:
            captured[height] = (st.encode(),
                                app.info().last_block_app_hash)

    _apply_chain(state, conns, store, chain, hook)
    reactor = BlockchainReactor(state, conns.consensus, store,
                                fast_sync=False)
    sw = make_switch(chain_id, {"blockchain": reactor}, moniker="source")
    return sw, state, store, app, captured


def _offer_verifier(chain):
    """The light-client cross-check hook built from the scenario's own
    chain: a manifest at height h must match the app_hash committed in
    the (verified) header at h+1."""
    headers = {block.height: block.header for block, _ps, _sc in chain}

    def verify(manifest):
        header = headers.get(manifest.height + 1)
        return (header is not None
                and verify_manifest_app_hash(manifest, header))
    return verify


# ===========================================================================
# snapshot-join (stress)
# ===========================================================================

N_JOIN_BLOCKS = 520
JOIN_INTERVAL = 100       # snapshots at 100..500; retention keeps 400+500
JOIN_TPB = 16             # enough per-block replay work that the full-sync
                          # baseline is dominated by linear replay


def _snapshot_join(ctx):
    chain_id = "chaos-snapshot-join"
    # 6 validators: per-block commit verification is the linear work
    # that makes full replay expensive — exactly the cost the snapshot
    # path's 19-block tail mostly skips
    privs, vs = fixtures.make_validators(6, seed=11)
    gen = fixtures.make_genesis(chain_id, privs)
    hashes = fixtures.kvstore_app_hashes(N_JOIN_BLOCKS,
                                         txs_per_block=JOIN_TPB)
    chain = fixtures.build_chain(privs, vs, chain_id, N_JOIN_BLOCKS,
                                 txs_per_block=JOIN_TPB,
                                 app_hashes=hashes)
    tip = N_JOIN_BLOCKS - 1   # fast-sync stops at tip-1: the last block
    #                           has no successor commit to verify it with
    snap_root = tempfile.mkdtemp(prefix="chaos-snapjoin-")
    ctx.snapshot_metrics("start")
    try:
        snap_store = SnapshotStore(snap_root, chunk_size=16 * 1024,
                                   retain=2)
        src_sw, _src_state, _src_store, _src_app, _ = \
            _snapshotting_source(chain_id, chain, gen, snap_store,
                                 JOIN_INTERVAL)
        snap_heights = [m.height for m in snap_store.list()]
        ctx.note("join.snapshots", heights=snap_heights)

        # -- baseline: the status-quo rejoin — full fast-sync from
        # genesis with every commit verified (the victim's disk is gone;
        # replaying its own blocks is not on the table)
        base_state = get_state(MemDB(), gen)
        base_app = create_app("kvstore")
        base_sw, _bc, _cons, base_store = harness.fastsync_syncer(
            chain_id, gen, batch_size=16, state=base_state, app=base_app)
        src_sw.start()
        base_sw.start()
        try:
            t0 = time.time()
            connect_switches(base_sw, src_sw)
            baseline_synced = harness.wait_until(
                lambda: base_store.height >= tip, timeout=180,
                poll=0.005)
            baseline_s = max(time.time() - t0, 1e-6)
        finally:
            base_sw.stop()

        # -- victim: restore from the source's snapshots, then fast-sync
        # only the tail snapshot_height -> tip
        syncer = StateSyncer(
            [StoreSource(src_sw.node_info.id, snap_store)],
            verify_offer=_offer_verifier(chain))
        vic_db = MemDB()
        vic_app = create_app("kvstore")
        t0 = time.time()
        vic_state, manifest = syncer.restore(vic_db, gen, vic_app)
        ctx.snapshot_metrics("restored")
        vic_store = BlockStore(MemDB())
        vic_store.bootstrap(manifest.height)
        vic_sw, _bc2, _cons2, vic_store = harness.fastsync_syncer(
            chain_id, gen, batch_size=16, state=vic_state,
            store=vic_store, app=vic_app)
        vic_sw.start()
        try:
            connect_switches(vic_sw, src_sw)
            victim_synced = harness.wait_until(
                lambda: vic_store.height >= tip, timeout=180,
                poll=0.005)
            victim_s = max(time.time() - t0, 1e-6)
        finally:
            vic_sw.stop()
            src_sw.stop()
        tail_blocks = vic_store.height - manifest.height
        REGISTRY.restore_replay_blocks.inc(max(tail_blocks, 0))
        ctx.snapshot_metrics("end")
    finally:
        shutil.rmtree(snap_root, ignore_errors=True)

    base_hash = base_app.info().last_block_app_hash
    vic_hash = vic_app.info().last_block_app_hash
    speedup = baseline_s / victim_s
    ctx.note("join.result", baseline_s=round(baseline_s, 3),
             victim_s=round(victim_s, 3), speedup=round(speedup, 2),
             restore_height=manifest.height, tail_blocks=tail_blocks)
    return {"baseline_synced": baseline_synced,
            "victim_synced": victim_synced,
            "restore_height": manifest.height,
            "tail_blocks": tail_blocks,
            "snap_heights": snap_heights,
            "parity_state": vic_state.encode() == base_state.encode(),
            "parity_app": bool(base_hash) and vic_hash == base_hash,
            "blamed": list(syncer.blamed),
            "budget_metrics": {
                "baseline_fullsync_s": round(baseline_s, 3),
                "victim_catchup_s": round(victim_s, 3),
                "catchup_speedup_x": round(speedup, 2)}}


def _join_safety_parity(ctx, obs):
    inv.require(obs["parity_state"],
                "snapshot-restored state + tail replay is NOT "
                "byte-identical to the full-replay state")
    inv.require(obs["parity_app"],
                "restored app recomputes a different app_hash than the "
                "fully-replayed app")
    inv.require(not obs["blamed"],
                f"honest snapshot source was blamed: {obs['blamed']}")
    # every fetched chunk went through hash verification, none rejected
    inv.metric_increased(ctx, "chunks_verified", until="restored")
    before = ctx.metrics("start") or {}
    after = ctx.metrics("restored") or {}
    inv.require(after.get("chunks_rejected", 0)
                == before.get("chunks_rejected", 0),
                "chunks were rejected on the clean snapshot-join path")


def _join_safety_short_tail(ctx, obs):
    inv.require(obs["restore_height"] >= 500,
                f"restored from height {obs['restore_height']}, below "
                f"the newest snapshot (crash height >= 500)")
    inv.require(0 <= obs["tail_blocks"] <= JOIN_INTERVAL,
                f"victim replayed {obs['tail_blocks']} blocks — more "
                f"than one snapshot interval ({JOIN_INTERVAL})")


def _join_safety_speedup(ctx, obs):
    bm = obs["budget_metrics"]
    inv.require(bm["catchup_speedup_x"] >= 10.0,
                f"snapshot-join is only {bm['catchup_speedup_x']}x "
                f"faster than full replay "
                f"(baseline {bm['baseline_fullsync_s']}s vs victim "
                f"{bm['victim_catchup_s']}s); the bar is 10x")


def _join_liveness(ctx, obs):
    inv.completed(obs, "baseline_synced",
                  "full-replay baseline sync to the tip")
    inv.completed(obs, "victim_synced",
                  "snapshot-restored victim's tail sync to the tip")


register(
    "snapshot-join",
    "a node with no disk rejoins a 520-block chain: restore from the "
    "newest snapshot (height 500, manifest app_hash cross-checked "
    "against a verified header, every chunk hash-verified) then "
    "fast-sync only the tail — byte-identical to a full replay and "
    ">=10x faster than the full fast-sync baseline measured on the "
    "same rig",
    safety=[("restore-parity", _join_safety_parity),
            ("tail-bounded-by-interval", _join_safety_short_tail),
            ("catchup-10x", _join_safety_speedup)],
    liveness=[("both-paths-catch-up", _join_liveness)],
    smoke=False, budget_s=420.0,
    budgets={"victim_catchup_s": {"max": 6.0},
             "baseline_fullsync_s": {"max": 60.0},
             "catchup_speedup_x": {"min": 10.0}})(_snapshot_join)


# ===========================================================================
# snapshot-tamper (stress)
# ===========================================================================

N_TAMPER_BLOCKS = 52
TAMPER_INTERVAL = 16      # snapshots at 16/32/48, retention keeps 32+48
TAMPER_TPB = 6


def _tamper_chunks(rng, snap_store, manifest):
    """Corrupt EVERY chunk of `manifest` in `snap_store` (seed-chosen
    byte, seed-chosen xor).  All of them: the fetcher assigns chunks
    round-robin, so a single bad chunk may legitimately never be asked
    of this peer — tampering all of them makes 'the tamperer served at
    least one bad chunk' deterministic."""
    sdir = snap_store.snapshot_dir(manifest.height)
    for i in range(manifest.chunks):
        path = os.path.join(sdir, f"chunk-{i:06d}.bin")
        with open(path, "rb") as f:
            data = bytearray(f.read())
        pos = rng.randrange(len(data))
        data[pos] ^= rng.randrange(1, 256)
        with open(path, "wb") as f:
            f.write(bytes(data))


def _forge_manifest(src_store, dst_store, honest: SnapshotManifest,
                    height: int) -> None:
    """PoTE-style forged finality claim: reuse the honest snapshot's
    chunks (so the root re-check passes) under a manifest claiming a
    LATER height with a fabricated app_hash.  Internally consistent —
    only the light-client cross-check can catch it."""
    src = src_store.snapshot_dir(honest.height)
    dst = dst_store.snapshot_dir(height)
    os.makedirs(dst, exist_ok=True)
    for name in os.listdir(src):
        if name != MANIFEST_NAME:
            shutil.copy(os.path.join(src, name), os.path.join(dst, name))
    forged = dataclasses.replace(honest, height=height,
                                 app_hash=bytes(range(20)))
    with open(os.path.join(dst, MANIFEST_NAME), "wb") as f:
        f.write(forged.encode_json())


def _snapshot_tamper(ctx):
    chain_id = "chaos-snapshot-tamper"
    privs, vs = fixtures.make_validators(2, seed=13)
    gen = fixtures.make_genesis(chain_id, privs)
    hashes = fixtures.kvstore_app_hashes(N_TAMPER_BLOCKS,
                                         txs_per_block=TAMPER_TPB)
    chain = fixtures.build_chain(privs, vs, chain_id, N_TAMPER_BLOCKS,
                                 txs_per_block=TAMPER_TPB,
                                 app_hashes=hashes)
    rng = ctx.rng("tamper")
    root = tempfile.mkdtemp(prefix="chaos-snaptamper-")
    ctx.snapshot_metrics("start")
    try:
        honest_store = SnapshotStore(os.path.join(root, "honest"),
                                     chunk_size=1024, retain=2)
        state = get_state(MemDB(), gen)
        app = create_app("kvstore")
        conns = ClientCreator(app).new_app_conns()
        block_store = BlockStore(MemDB())
        captured: dict[int, tuple[bytes, bytes]] = {}

        def hook(height, st):
            if height % TAMPER_INTERVAL == 0:
                honest_store.create(st, app.snapshot_state())
                captured[height] = (st.encode(),
                                    app.info().last_block_app_hash)

        _apply_chain(state, conns, block_store, chain, hook)
        best = honest_store.best()

        # tamperer: honest manifest, corrupted chunk bytes
        tamper_store = SnapshotStore(os.path.join(root, "tamper"),
                                     chunk_size=1024, retain=2)
        shutil.rmtree(tamper_store.root_dir)
        shutil.copytree(honest_store.root_dir, tamper_store.root_dir)
        _tamper_chunks(rng, tamper_store, best)
        # forger: honest chunks, forged manifest at a later height —
        # its higher height makes it the FIRST offer the victim tries
        forge_store = SnapshotStore(os.path.join(root, "forge"),
                                    chunk_size=1024, retain=2)
        _forge_manifest(honest_store, forge_store, best, best.height + 2)

        # the victim's switch: bans from statesync blame land here
        sw = make_switch(chain_id, {}, moniker="victim")
        sources = [StoreSource("forger", forge_store),
                   StoreSource("tamperer", tamper_store),
                   StoreSource("honest", honest_store)]
        syncer = StateSyncer(sources,
                             report_misbehavior=sw.report_misbehavior,
                             verify_offer=_offer_verifier(chain))
        vic_app = create_app("kvstore")
        t0 = time.time()
        vic_state, manifest = syncer.restore(MemDB(), gen, vic_app)
        restore_s = max(time.time() - t0, 1e-6)
        ctx.snapshot_metrics("end")
    finally:
        shutil.rmtree(root, ignore_errors=True)

    ref_state, ref_app_hash = captured[best.height]
    before = ctx.metrics("start") or {}
    after = ctx.metrics("end") or {}
    rejected_delta = (after.get("chunks_rejected", 0)
                      - before.get("chunks_rejected", 0))
    ctx.note("tamper.result", restored_height=manifest.height,
             blamed=list(syncer.blamed), rejected=rejected_delta,
             restore_s=round(restore_s, 3))
    return {"restored": True,
            "restored_height": manifest.height,
            "expected_height": best.height,
            "chunks": manifest.chunks,
            "parity_state": vic_state.encode() == ref_state,
            "parity_app": (bool(ref_app_hash)
                           and vic_app.info().last_block_app_hash
                           == ref_app_hash),
            "blamed": list(syncer.blamed),
            "forger_banned": sw.is_banned("forger"),
            "tamperer_banned": sw.is_banned("tamperer"),
            "honest_banned": sw.is_banned("honest"),
            "rejected_delta": rejected_delta,
            "budget_metrics": {
                "tamper_restore_s": round(restore_s, 3),
                "tamper_chunks_rejected": float(rejected_delta)}}


def _tamper_safety_no_silent_acceptance(ctx, obs):
    # zero silent acceptance: the restore came from the HONEST snapshot
    # (not the forged higher offer), is byte-identical to the state the
    # source snapshotted, and every corrupted chunk that was served got
    # hash-rejected and blamed rather than applied
    inv.require(obs["restored_height"] == obs["expected_height"],
                f"victim restored from height {obs['restored_height']} "
                f"— the forged offer, not the honest snapshot at "
                f"{obs['expected_height']}")
    inv.require(obs["parity_state"] and obs["parity_app"],
                "restored state/app diverges from the snapshotted "
                "source state — corrupted bytes were silently accepted")
    inv.require(obs["rejected_delta"] >= 1,
                "the tamperer's corrupted chunks were never rejected — "
                "hash verification did not fire")
    inv.metric_increased(ctx, "chunks_rejected")
    inv.metric_increased(ctx, "chunks_verified")


def _tamper_safety_blame(ctx, obs):
    inv.require(obs["forger_banned"],
                "the forged-manifest peer was not banned (the "
                "light-client cross-check is a proven lie)")
    inv.require(obs["tamperer_banned"],
                "the chunk-corrupting peer was not banned")
    inv.require(not obs["honest_banned"],
                "the honest snapshot provider was banned")
    blamed_peers = {p for p, _r in obs["blamed"]}
    inv.require("honest" not in blamed_peers,
                f"the honest provider was blamed: {obs['blamed']}")
    inv.require({"forger", "tamperer"} <= blamed_peers,
                f"missing blame entries: {obs['blamed']}")


def _tamper_liveness(ctx, obs):
    inv.completed(obs, "restored",
                  "restore via the good peer after rejecting the "
                  "forged and corrupted offers")


register(
    "snapshot-tamper",
    "PoTE-style snapshot adversaries: a forged manifest claiming a "
    "later height with a fabricated app_hash (caught by the "
    "light-client cross-check) and a peer serving corrupted chunks "
    "under an honest manifest (caught by per-chunk hash verification); "
    "both peers are banned, the restore completes from the honest peer "
    "byte-identically, and not one corrupted byte is accepted",
    safety=[("no-silent-acceptance", _tamper_safety_no_silent_acceptance),
            ("liars-banned-honest-spared", _tamper_safety_blame)],
    liveness=[("restore-completes", _tamper_liveness)],
    smoke=False, budget_s=120.0,
    budgets={"tamper_restore_s": {"max": 30.0},
             "tamper_chunks_rejected": {"min": 1.0}})(_snapshot_tamper)


# ===========================================================================
# snapshot-torn-tail (smoke)
# ===========================================================================

N_TORN_BLOCKS = 12
TORN_INTERVAL = 4


class _CrashMidCreate(Exception):
    """The in-process stand-in for a crash at a snapshot fail point."""


def _snapshot_torn_tail(ctx):
    chain_id = "chaos-snapshot-torn"
    privs, vs = fixtures.make_validators(2, seed=17)
    gen = fixtures.make_genesis(chain_id, privs)
    hashes = fixtures.kvstore_app_hashes(N_TORN_BLOCKS)
    chain = fixtures.build_chain(privs, vs, chain_id, N_TORN_BLOCKS,
                                 app_hashes=hashes)
    rng = ctx.rng("torn")
    # seed-chosen crash site: mid-chunk-write or after the chunks but
    # before the manifest — either way no manifest lands
    crash_site = rng.choice(["Snapshot.chunkWritten",
                             "Snapshot.chunksWritten"])
    ctx.plan("torn.crash", site=crash_site)
    root = tempfile.mkdtemp(prefix="chaos-snaptorn-")
    try:
        store = SnapshotStore(root, chunk_size=512, retain=3)
        state = get_state(MemDB(), gen)
        app = create_app("kvstore")
        conns = ClientCreator(app).new_app_conns()
        block_store = BlockStore(MemDB())
        captured: dict[int, tuple[bytes, bytes]] = {}
        crashed: list[str] = []

        def hook(height, st):
            if height % TORN_INTERVAL == 0:
                if height == N_TORN_BLOCKS:
                    # crash mid-create of the newest snapshot
                    def boom(name, idx):
                        raise _CrashMidCreate(name)
                    fail.set_callback(boom)
                    os.environ["TM_FAIL_POINT"] = crash_site
                    try:
                        store.create(st, app.snapshot_state())
                    except _CrashMidCreate as e:
                        crashed.append(str(e))
                    finally:
                        os.environ.pop("TM_FAIL_POINT", None)
                        fail.set_callback(None)
                else:
                    store.create(st, app.snapshot_state())
            captured[height] = (st.encode(),
                                app.info().last_block_app_hash)

        _apply_chain(state, conns, block_store, chain, hook)

        # bit-rot the previous snapshot's manifest too (seed-chosen
        # truncation): the CRC frame must reject it, leaving only the
        # oldest snapshot intact
        torn_h = N_TORN_BLOCKS - TORN_INTERVAL
        mpath = os.path.join(store.snapshot_dir(torn_h), MANIFEST_NAME)
        raw = open(mpath, "rb").read()
        with open(mpath, "wb") as f:
            f.write(raw[:rng.randrange(1, len(raw))])
        valid, rejects = store.scan()
        valid_heights = [m.height for m in valid]
        reject_reasons = [why for _d, why in rejects]
        ctx.note("torn.scan", valid=valid_heights,
                 rejects=reject_reasons, crashed=crashed)

        # restore from what survived, then replay the short tail
        syncer = StateSyncer([StoreSource("local", store)],
                             verify_offer=_offer_verifier(chain))
        vic_app = create_app("kvstore")
        vic_state, manifest = syncer.restore(MemDB(), gen, vic_app)
        vic_conns = ClientCreator(vic_app).new_app_conns()
        vic_store = BlockStore(MemDB())
        vic_store.bootstrap(manifest.height)
        for block, ps, _seen in chain[manifest.height:]:
            execution.apply_block(vic_state, None, vic_conns.consensus,
                                  block, ps.header,
                                  execution.MockMempool(),
                                  check_last_commit=False)
        REGISTRY.restore_replay_blocks.inc(
            N_TORN_BLOCKS - manifest.height)
    finally:
        shutil.rmtree(root, ignore_errors=True)

    ref_state, ref_app_hash = captured[N_TORN_BLOCKS]
    return {"crashed": bool(crashed),
            "crash_site": crash_site,
            "valid_heights": valid_heights,
            "reject_reasons": reject_reasons,
            "restored_height": manifest.height,
            "replayed": N_TORN_BLOCKS - manifest.height,
            "parity_state": vic_state.encode() == ref_state,
            "parity_app": (bool(ref_app_hash)
                           and vic_app.info().last_block_app_hash
                           == ref_app_hash),
            "blamed": list(syncer.blamed)}


def _torn_safety_discard(ctx, obs):
    inv.require(obs["crashed"],
                f"the fail point {obs['crash_site']} never fired — the "
                f"torn-create injection did not happen")
    inv.require(obs["valid_heights"] == [TORN_INTERVAL],
                f"scan kept {obs['valid_heights']} — expected only the "
                f"oldest intact snapshot [{TORN_INTERVAL}] after a torn "
                f"create and a truncated manifest")
    inv.require(len(obs["reject_reasons"]) == 2,
                f"expected 2 rejected snapshots (torn create + "
                f"truncated manifest), got {obs['reject_reasons']}")


def _torn_safety_parity(ctx, obs):
    inv.require(obs["restored_height"] == TORN_INTERVAL,
                f"restored from {obs['restored_height']}, not the "
                f"intact snapshot at {TORN_INTERVAL}")
    inv.require(obs["parity_state"] and obs["parity_app"],
                "restore + tail replay diverges from the source state "
                "at the tip")
    inv.require(not obs["blamed"],
                f"local snapshot store was blamed: {obs['blamed']}")


def _torn_liveness(ctx, obs):
    inv.require(obs["replayed"] == N_TORN_BLOCKS - TORN_INTERVAL,
                f"tail replay covered {obs['replayed']} blocks, "
                f"expected {N_TORN_BLOCKS - TORN_INTERVAL}")
    inv.completed(obs, "parity_state",
                  "recovery from the previous intact snapshot")


register(
    "snapshot-torn-tail",
    "crash mid-snapshot-write (seed-chosen fail point) plus a "
    "bit-rotted manifest: both torn snapshots are discarded on scan "
    "(no manifest / CRC mismatch), recovery restores from the previous "
    "intact snapshot and replays the tail to the tip byte-identically",
    safety=[("torn-snapshots-discarded", _torn_safety_discard),
            ("recovery-parity", _torn_safety_parity)],
    liveness=[("tail-replay-completes", _torn_liveness)],
    smoke=True, budget_s=60.0)(_snapshot_torn_tail)
