"""Seed-deterministic fault-scenario engine.

A *scenario* is a declarative composition of fault injectors (byzantine
vote streams, network partitions, crash-restart storms, device-fault
storms) plus a post-mortem: safety and liveness invariants checked
against flight-recorder and metric evidence after the run.

The replay contract
-------------------
Every scenario runs from ONE integer seed.  All injector randomness is
derived from it through `utils.chaos.derive_seed(seed, *labels)` — the
per-injector RNGs, the `FuzzedConnection` streams, the crash schedule,
the byzantine height sets.  The engine keeps an *event log* with two
streams:

- **plan events** (`ctx.plan(...)`): the injected-fault schedule as
  derived from the seed — which heights equivocate, which window the
  partition covers, which chaos spec the crypto ladder gets, which RNG
  seeds were handed out.  Plan events are a pure function of
  (scenario, seed): their canonical-JSON sha256 is the *event log
  hash*, and two runs with the same seed MUST produce the same hash
  (tier-1 asserts this).
- **notes** (`ctx.note(...)`): what actually happened at runtime
  (timing-dependent: observed heights, breaker trips, eviction order).
  Notes are dumped for triage but never hashed.

Post-mortem + artifacts
-----------------------
After the scenario body returns, the engine runs its registered safety
and liveness invariants.  On ANY failure (body exception or invariant
violation) it dumps a per-scenario artifact directory:

    <artifacts>/<scenario>-seed<N>/
        trace.json      flight-recorder Chrome trace (load in Perfetto)
        metrics.json    phase-labeled REGISTRY snapshots (incl. per-rung
                        crypto counters)
        events.json     the event log: plan stream, hash, and notes
        result.json     manifest: outcome, failures, seed — the replay
                        input for `cli chaos replay`

Triage flow: read result.json for the failed invariant, open trace.json
in Perfetto against events.json's plan timeline, then re-run bit-
identically with `cli chaos run --scenario <name> --seed <N>`.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import threading
import time

from tendermint_tpu.utils import chaos as chaosmod
from tendermint_tpu.utils import lockwitness
from tendermint_tpu.utils import tracing
from tendermint_tpu.utils.log import get_logger
from tendermint_tpu.utils.metrics import REGISTRY

log = get_logger("scenarios")

# Fixed default seed for the faults tier: CI runs are reproducible by
# default, and a red run's artifact names tell you the seed to replay.
DEFAULT_SEED = 20260806


class InvariantViolation(AssertionError):
    """A scenario post-mortem assertion failed.  The message must carry
    the evidence (heights, hashes, metric values) — it is what lands in
    result.json for triage."""


class EventLog:
    """Deterministic plan stream + timing-dependent note stream.

    Concurrency: notes may arrive from any injector thread (the lock
    serializes them); plan events may NOT — their ORDER is part of the
    hash, and thread interleaving would make it timing-dependent.  The
    engine seals the plan stream while scheduled injectors run
    concurrently (`sealed_plan`), so a plan() from inside a concurrent
    injector fails loudly instead of silently breaking replay."""

    def __init__(self):
        self._plan: list[dict] = []
        self._notes: list[dict] = []
        self._sealed = False
        self._lock = lockwitness.new_lock("scenarios.eventlog",
                                          reentrant=False)

    def plan(self, event: str, **fields) -> None:
        """Record one planned injection.  Fields must be JSON-safe and
        derived only from the seed (never wall-clock) — they are hashed
        into the determinism contract."""
        with self._lock:
            if self._sealed:
                raise RuntimeError(
                    f"plan event {event!r} emitted while the plan stream "
                    f"is sealed (concurrent injectors are running): plan "
                    f"order would be timing-dependent and break the "
                    f"event_log_hash replay contract — derive the whole "
                    f"schedule before InjectorSchedule.run()")
            self._plan.append({"event": event, **fields})

    def note(self, event: str, **fields) -> None:
        """Record a runtime observation (not hashed)."""
        with self._lock:
            self._notes.append({"t": round(time.time(), 6),
                                "event": event, **fields})

    @contextlib.contextmanager
    def sealed_plan(self):
        """Freeze the plan stream (plan() raises) while concurrent
        injector threads run; notes stay open."""
        with self._lock:
            self._sealed = True
        try:
            yield
        finally:
            with self._lock:
                self._sealed = False

    def hash(self) -> str:
        blob = json.dumps(self._plan, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()

    def to_dict(self) -> dict:
        return {"hash": self.hash(), "plan": list(self._plan),
                "notes": list(self._notes)}


class ScenarioContext:
    """What a scenario body (and its injectors) gets to work with."""

    def __init__(self, scenario: "Scenario", seed: int):
        self.scenario = scenario
        self.seed = seed
        self.log = EventLog()
        self.recorder = tracing.RECORDER
        self.metric_phases: list[dict] = []
        self._rngs: dict[str, object] = {}
        # the installed crypto backend object for this run (run_scenario
        # sets it): bodies drive ladder probes / attach chaos through it
        self.backend = None
        self.backend_name: str | None = None

    # -- derived randomness ---------------------------------------------
    def derive_seed(self, *labels: str) -> int:
        return chaosmod.derive_seed(self.seed, self.scenario.name, *labels)

    def rng(self, name: str):
        """A named `random.Random` derived from the scenario seed; the
        derivation is logged as a plan event so the seed handed to each
        injector is part of the hashed schedule."""
        if name not in self._rngs:
            import random
            child = self.derive_seed("rng", name)
            self.log.plan("rng", name=name, seed=child)
            self._rngs[name] = random.Random(child)
        return self._rngs[name]

    # -- event log shorthands -------------------------------------------
    def plan(self, event: str, **fields) -> None:
        self.log.plan(event, **fields)

    def note(self, event: str, **fields) -> None:
        self.log.note(event, **fields)

    # -- evidence capture ------------------------------------------------
    def snapshot_metrics(self, phase: str) -> dict:
        """Capture a phase-labeled REGISTRY snapshot (includes the
        rung-labeled crypto counters) — the metric evidence invariants
        assert against."""
        snap = {"phase": phase, "metrics": REGISTRY.snapshot()}
        self.metric_phases.append(snap)
        self.recorder.instant("scenario.phase", phase=phase)
        return snap

    def metrics(self, phase: str) -> dict | None:
        for snap in self.metric_phases:
            if snap["phase"] == phase:
                return snap["metrics"]
        return None

    # -- composable injector schedules ----------------------------------
    def schedule(self, label: str = "schedule") -> "InjectorSchedule":
        """A combined-adversary schedule: declare several injectors with
        seed-derived phase offsets, then run them CONCURRENTLY."""
        return InjectorSchedule(self, label)


class InjectorSchedule:
    """Multiple injectors running concurrently with seed-derived phase
    offsets, folded into the one event_log_hash replay contract.

    Declaration (`add`) is single-threaded and emits the plan events:
    each entry's offset = `after` + U(0, jitter_s) drawn from the
    scenario seed, so the combined schedule replays bit-identically.
    Execution (`run`) spawns one thread per entry, SEALS the plan stream
    for the duration (injector bodies must have derived their whole
    schedule already — runtime effects record notes only), sleeps each
    entry to its offset, and joins them all.  Injector exceptions are
    collected and re-raised after the join so one broken injector never
    strands the others' threads."""

    def __init__(self, ctx: ScenarioContext, label: str = "schedule"):
        self.ctx = ctx
        self.label = label
        self._entries: list[tuple[str, float, object]] = []

    def add(self, name: str, fn, *, after: float = 0.0,
            jitter_s: float = 0.0) -> float:
        """Declare injector `name` (a zero-arg callable) to fire at
        `after` + seed-derived U(0, jitter_s) seconds into run().
        Returns the planned offset."""
        offset = float(after)
        if jitter_s > 0.0:
            rng = self.ctx.rng(f"{self.label}.{name}")
            offset += rng.random() * float(jitter_s)
        offset = round(offset, 6)
        self.ctx.plan("injector-schedule", schedule=self.label,
                      name=name, offset_s=offset)
        self._entries.append((name, offset, fn))
        return offset

    def run(self, join_timeout_s: float = 120.0) -> None:
        """Fire every declared injector at its offset, concurrently."""
        errors: list[tuple[str, BaseException]] = []
        err_lock = threading.Lock()

        def runner(name: str, offset: float, fn) -> None:
            time.sleep(offset)
            self.ctx.note("injector.fire", schedule=self.label, name=name,
                          offset_s=offset)
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 - surfaced after join
                with err_lock:
                    errors.append((name, e))
                self.ctx.note("injector.error", schedule=self.label,
                              name=name,
                              error=f"{type(e).__name__}: {e}")
            else:
                self.ctx.note("injector.done", schedule=self.label,
                              name=name)

        threads = [threading.Thread(target=runner, args=e, daemon=True,
                                    name=f"injector-{e[0]}")
                   for e in self._entries]
        with self.ctx.log.sealed_plan():
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=join_timeout_s)
        alive = [t.name for t in threads if t.is_alive()]
        if alive:
            raise RuntimeError(
                f"injector schedule {self.label!r} timed out: "
                f"{alive} still running after {join_timeout_s}s")
        if errors:
            name, exc = errors[0]
            raise RuntimeError(
                f"injector {name!r} in schedule {self.label!r} failed: "
                f"{type(exc).__name__}: {exc}") from exc


# -- scenario crypto backends ----------------------------------------------
#
# Rigs are backend-parametric: every scenario declares a default rung
# (python unless it says otherwise) and TM_SCENARIO_BACKEND / an explicit
# run_scenario(backend=...) override walks the SupervisedBackend ladder
# instead.  "python" is a bare PythonBackend (bit-deterministic smoke
# tier); "tpu"/"ladder" and "native" build the supervised ladder starting
# at that rung (skipping unavailable rungs, always ending on the python
# floor); "rig" is a two-rung supervised ladder whose device-role rung is
# a PythonBackend — the deterministic chaos-capable ladder big rigs run
# on hardware-free CI, with the same breaker/demotion machinery as the
# real device ladder.
KNOWN_BACKENDS = ("python", "tpu", "native", "ladder", "rig")
DEFAULT_SCENARIO_BACKEND = "python"
SCENARIO_BACKEND_ENV = "TM_SCENARIO_BACKEND"


def resolve_backend(sc: "Scenario", override: str | None = None) -> str:
    """Precedence: explicit override > TM_SCENARIO_BACKEND > the
    scenario's declared default."""
    name = (override or os.environ.get(SCENARIO_BACKEND_ENV, "").strip()
            or sc.backend)
    if name not in KNOWN_BACKENDS:
        raise ValueError(f"unknown scenario backend {name!r} "
                         f"(known: {sorted(KNOWN_BACKENDS)})")
    return name


def _make_scenario_backend(name: str):
    from tendermint_tpu.crypto import backend as cb
    from tendermint_tpu.crypto.supervised import SupervisedBackend
    if name == "python":
        return cb.PythonBackend()
    if name == "rig":
        return SupervisedBackend(
            [("dev", cb.PythonBackend()), ("python", cb.PythonBackend())],
            breaker_threshold=2, breaker_cooldown_s=0.5,
            retries=0, call_timeout_s=30.0)
    primary = "tpu" if name == "ladder" else name
    return SupervisedBackend.build(primary)


@contextlib.contextmanager
def scenario_backend(name: str):
    """Install the resolved backend as the process-wide crypto backend
    for the duration of a scenario run; yields the backend object (also
    exposed as ctx.backend so bodies can drive ladder probes)."""
    from tendermint_tpu.crypto import backend as cb
    be = _make_scenario_backend(name)
    with cb._lock:
        old = cb._current
        cb._current = be
    try:
        yield be
    finally:
        with cb._lock:
            cb._current = old


class Scenario:
    """A registered scenario: body + named safety/liveness invariants.

    `body(ctx)` composes injectors and returns a JSON-safe observations
    dict; each invariant is `(name, fn)` with `fn(ctx, obs)` raising
    InvariantViolation on failure.  Every shipped scenario must carry at
    least one safety AND one liveness invariant — registration enforces
    it so a scenario cannot silently ship without a post-mortem."""

    def __init__(self, name: str, description: str, body,
                 safety: list, liveness: list, smoke: bool = False,
                 budget_s: float | None = None,
                 backend: str | None = None,
                 budgets: dict | None = None):
        if not safety or not liveness:
            raise ValueError(
                f"scenario {name!r} needs >=1 safety and >=1 liveness "
                f"invariant (got {len(safety)}/{len(liveness)})")
        self.name = name
        self.description = description
        self.body = body
        self.safety = list(safety)
        self.liveness = list(liveness)
        self.smoke = smoke
        # declared wall-clock budget per run: a run over budget is a
        # BUDGET BREACH (soak exits nonzero on it, the chaos ledger
        # records it) — a fault-path latency regression bisects exactly
        # like a correctness regression
        self.budget_s = float(budget_s) if budget_s is not None else (
            DEFAULT_SMOKE_BUDGET_S if smoke else DEFAULT_STRESS_BUDGET_S)
        # default crypto backend for the rig (see KNOWN_BACKENDS);
        # python keeps the smoke tier deterministic, big rigs declare a
        # supervised ladder, TM_SCENARIO_BACKEND overrides at run time
        self.backend = backend or DEFAULT_SCENARIO_BACKEND
        if self.backend not in KNOWN_BACKENDS:
            raise ValueError(
                f"scenario {name!r}: unknown backend {self.backend!r} "
                f"(known: {sorted(KNOWN_BACKENDS)})")
        # metric-level budgets alongside the wall-clock one: each entry
        # maps a metric the body reports (obs['budget_metrics'][name] or
        # obs[name]) to a bound — a bare number is a max, or an explicit
        # {'max': x} / {'min': x}.  A violated OR MISSING metric is a
        # budget breach, ledgered per seed like the wall-clock budget.
        self.budgets = _normalize_budgets(name, budgets)


# default declared budgets (seconds per run) when a scenario doesn't
# declare its own via register(budget_s=...)
DEFAULT_SMOKE_BUDGET_S = 120.0
DEFAULT_STRESS_BUDGET_S = 420.0

SCENARIOS: dict[str, Scenario] = {}


def _normalize_budgets(name: str, budgets: dict | None) -> dict:
    """Validate + canonicalize a metric-budget declaration into
    {metric: {"max": float} | {"min": float} | both}."""
    out: dict[str, dict] = {}
    for metric, spec in (budgets or {}).items():
        if isinstance(spec, bool) or not isinstance(
                spec, (int, float, dict)):
            raise ValueError(
                f"scenario {name!r}: budget for {metric!r} must be a "
                f"number (max) or a {{'max'/'min': number}} dict, "
                f"got {spec!r}")
        if isinstance(spec, dict):
            if not spec or not set(spec) <= {"max", "min"}:
                raise ValueError(
                    f"scenario {name!r}: budget for {metric!r} allows "
                    f"only 'max'/'min' keys, got {sorted(spec)}")
            out[metric] = {k: float(v) for k, v in spec.items()}
        else:
            out[metric] = {"max": float(spec)}
    return out


def register(name: str, description: str, safety: list, liveness: list,
             smoke: bool = False, budget_s: float | None = None,
             backend: str | None = None, budgets: dict | None = None):
    """Decorator: `@register("byz-equivocation", "...", safety=[...],
    liveness=[...])` over the scenario body."""
    def deco(fn):
        if name in SCENARIOS:
            raise ValueError(f"duplicate scenario {name!r}")
        SCENARIOS[name] = Scenario(name, description, fn,
                                   safety, liveness, smoke=smoke,
                                   budget_s=budget_s, backend=backend,
                                   budgets=budgets)
        return fn
    return deco


class ScenarioResult:
    def __init__(self, name: str, seed: int, ok: bool, failures: list[str],
                 event_log_hash: str, duration_s: float,
                 observations: dict, artifact_dir: str | None,
                 budget_s: float | None = None,
                 budget_breaches: list[str] | None = None,
                 backend: str | None = None,
                 budget_metrics: dict | None = None):
        self.name = name
        self.seed = seed
        self.ok = ok
        self.failures = failures
        self.event_log_hash = event_log_hash
        self.duration_s = duration_s
        self.observations = observations
        self.artifact_dir = artifact_dir
        self.budget_s = budget_s
        # breaches are tracked apart from invariant failures: the run's
        # VERDICT stays about correctness, but soak exits nonzero on both
        self.budget_breaches = list(budget_breaches or [])
        self.backend = backend
        # per-metric verdicts: {metric: {value, max?, min?, ok}} — what
        # the per-seed chaos-ledger entries carry next to the wall clock
        self.budget_metrics = dict(budget_metrics or {})

    def to_dict(self) -> dict:
        return {"scenario": self.name, "seed": self.seed, "ok": self.ok,
                "failures": self.failures,
                "event_log_hash": self.event_log_hash,
                "duration_s": round(self.duration_s, 3),
                "budget_s": self.budget_s,
                "budget_breaches": self.budget_breaches,
                "backend": self.backend,
                "budget_metrics": _json_safe(self.budget_metrics),
                "observations": _json_safe(self.observations),
                "artifact_dir": self.artifact_dir}


def _json_safe(obj):
    """Coerce observation values for the manifest: bytes become hex,
    unknown objects their repr — a dump must never fail the dumper."""
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (bytes, bytearray)):
        return obj.hex()
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


def artifacts_root(override: str | None = None) -> str:
    return (override or os.environ.get("TM_SCENARIO_ARTIFACTS")
            or os.path.join(os.getcwd(), "chaos_artifacts"))


def _dump_artifacts(ctx: ScenarioContext, result: ScenarioResult,
                    root: str) -> str:
    d = os.path.join(root, f"{ctx.scenario.name}-seed{ctx.seed}")
    os.makedirs(d, exist_ok=True)
    ctx.recorder.dump(os.path.join(d, "trace.json"))
    files = [("metrics.json", ctx.metric_phases),
             ("events.json", ctx.log.to_dict()),
             ("result.json", result.to_dict())]
    # merged consensus timeline + doctor, rebuilt from the lifecycle
    # spans in the recorder ring — so any failing/breaching rig run
    # ships its per-node waterfall in the triage bundle.  Best-effort:
    # a telemetry bug must never eat the primary artifacts.
    try:
        from tendermint_tpu import telemetry
        records = telemetry.records_from_spans(ctx.recorder.snapshot())
        if records:
            timeline = telemetry.build_timeline(records)
            files.append(("timeline.json",
                          telemetry.to_chrome_trace(timeline)))
            files.append(("consensus_doctor.json",
                          telemetry.consensus_doctor(timeline)))
    except Exception:
        pass
    for fname, payload in files:
        tmp = os.path.join(d, fname + ".tmp")
        with open(tmp, "w") as f:
            json.dump(_json_safe(payload), f, indent=1)
        os.replace(tmp, os.path.join(d, fname))
    return d


def _check_metric_budgets(sc: Scenario, obs: dict) -> tuple[list[str], dict]:
    """Evaluate the scenario's declared metric budgets against the
    body's reported values.  Returns (breach strings, per-metric
    verdicts).  A metric the body failed to report is itself a breach —
    a budget that silently stopped being measured must not read as
    green."""
    breaches: list[str] = []
    verdicts: dict[str, dict] = {}
    reported = obs.get("budget_metrics") or {}
    for metric, spec in sc.budgets.items():
        val = reported.get(metric, obs.get(metric))
        verdict = dict(spec)
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            verdict.update(value=None, ok=False)
            breaches.append(
                f"metric {metric} missing from observations "
                f"(declared budget {spec}) — the budget was not measured")
        else:
            ok = True
            if "max" in spec and val > spec["max"]:
                ok = False
                breaches.append(f"metric {metric}={val:g} over declared "
                                f"max {spec['max']:g}")
            if "min" in spec and val < spec["min"]:
                ok = False
                breaches.append(f"metric {metric}={val:g} under declared "
                                f"min {spec['min']:g}")
            verdict.update(value=val, ok=ok)
        verdicts[metric] = verdict
    return breaches, verdicts


def run_scenario(name: str, seed: int = DEFAULT_SEED,
                 artifacts: str | None = None,
                 keep_artifacts: bool = False,
                 backend: str | None = None) -> ScenarioResult:
    """Run one registered scenario end to end: install the ChaosConfig
    and the resolved crypto backend, execute the body, snapshot metrics,
    run the safety+liveness post-mortem, check wall-clock and metric
    budgets, and dump artifacts on any failure OR budget breach (always,
    when `keep_artifacts`).  Never raises on scenario failure — the
    result carries the verdict; raises only on unknown scenario or
    backend names."""
    sc = SCENARIOS.get(name)
    if sc is None:
        raise KeyError(f"unknown scenario {name!r}; "
                       f"known: {sorted(SCENARIOS)}")
    backend_name = resolve_backend(sc, backend)
    ctx = ScenarioContext(sc, seed)
    ctx.plan("scenario", name=name, seed=seed)
    # part of the hashed schedule: a replay on a different rung is a
    # DIFFERENT experiment and must not report MATCH
    ctx.plan("backend", name=backend_name)
    ctx.backend_name = backend_name
    prev_cfg = chaosmod.install(chaosmod.ChaosConfig(seed=seed))
    failures: list[str] = []
    obs: dict = {}
    t0 = time.perf_counter()
    ctx.snapshot_metrics("start")
    try:
        with scenario_backend(backend_name) as be:
            ctx.backend = be
            with ctx.recorder.span("scenario.run", cat=tracing.CAT_NONE,
                                   scenario=name, seed=seed):
                try:
                    obs = sc.body(ctx) or {}
                except InvariantViolation as e:
                    failures.append(f"body: {e}")
                except Exception as e:  # noqa: BLE001 - the post-mortem
                    # must still run and the trace must still dump on ANY
                    # failure
                    log.error("scenario body crashed", scenario=name,
                              error=f"{type(e).__name__}: {e}")
                    failures.append(f"body: {type(e).__name__}: {e}")
            ctx.snapshot_metrics("end")
            for kind, invariants in (("safety", sc.safety),
                                     ("liveness", sc.liveness)):
                for inv_name, fn in invariants:
                    try:
                        fn(ctx, obs)
                        ctx.note("invariant", name=inv_name, kind=kind,
                                 ok=True)
                    except AssertionError as e:
                        failures.append(f"{kind}:{inv_name}: {e}")
                        ctx.note("invariant", name=inv_name, kind=kind,
                                 ok=False, error=str(e))
                    except Exception as e:  # noqa: BLE001 - an invariant
                        # that crashes is a failed invariant, not a
                        # passed one
                        failures.append(
                            f"{kind}:{inv_name}: {type(e).__name__}: {e}")
                        ctx.note("invariant", name=inv_name, kind=kind,
                                 ok=False, error=f"{type(e).__name__}: {e}")
    finally:
        ctx.backend = None
        chaosmod.install(prev_cfg)
    duration_s = time.perf_counter() - t0
    breaches: list[str] = []
    if sc.budget_s is not None and duration_s > sc.budget_s:
        breaches.append(
            f"wall-clock {duration_s:.1f}s over declared budget "
            f"{sc.budget_s:.1f}s")
    metric_breaches, budget_metrics = _check_metric_budgets(sc, obs)
    breaches.extend(metric_breaches)
    result = ScenarioResult(
        name=name, seed=seed, ok=not failures, failures=failures,
        event_log_hash=ctx.log.hash(),
        duration_s=duration_s,
        observations=obs, artifact_dir=None,
        budget_s=sc.budget_s, budget_breaches=breaches,
        backend=backend_name, budget_metrics=budget_metrics)
    if breaches:
        log.warn("scenario over budget", scenario=name, seed=seed,
                    duration_s=round(duration_s, 1), budget_s=sc.budget_s,
                    breaches=len(breaches))
    # a budget breach files the same durable triage bundle an invariant
    # failure does: nightly CI red must always leave the evidence behind
    if failures or breaches or keep_artifacts:
        try:
            result.artifact_dir = _dump_artifacts(
                ctx, result, artifacts_root(artifacts))
            log.info("scenario artifacts dumped", scenario=name,
                     dir=result.artifact_dir)
        except OSError as e:
            log.error("scenario artifact dump failed", scenario=name,
                      error=str(e))
    return result


# -- seed-sweep soak ------------------------------------------------------

CHAOS_LEDGER_SCHEMA = "tpu-bft-chaos-ledger/1"
# one line per (scenario, seed) run: the per-seed budget verdicts
# (commit_latency_p99, rounds_per_height, ...) next to the wall clock,
# so a single seed's regression is greppable without re-running the sweep
CHAOS_RUN_SCHEMA = "tpu-bft-chaos-run/1"
DEFAULT_CHAOS_LEDGER = "CHAOS_LEDGER.jsonl"


def parse_seed_range(spec: str) -> list[int]:
    """`"A:B"` -> half-open [A, B) (so `0:25` is 25 seeds); a bare
    integer is a single-seed range."""
    spec = spec.strip()
    try:
        if ":" not in spec:
            return [int(spec)]
        a_s, b_s = spec.split(":", 1)
        a, b = int(a_s), int(b_s)
    except ValueError:
        raise ValueError(
            f"bad seed range {spec!r}: expected 'A:B' (half-open) or a "
            f"single integer") from None
    if b <= a:
        raise ValueError(f"bad seed range {spec!r}: B must be > A "
                         f"(half-open [A, B))")
    return list(range(a, b))


def run_sweep(names: list[str], seeds: list[int],
              artifacts: str | None = None, keep_artifacts: bool = False,
              ledger_path: str | None = None,
              progress=None, backend: str | None = None) -> dict:
    """Soak: run every scenario in `names` across every seed in `seeds`,
    aggregate per-scenario stats, and (unless `ledger_path` is None)
    append one per-run chaos-ledger line per (scenario, seed) — carrying
    the metric-budget verdicts — plus an aggregate entry whose
    per-scenario `runs_per_sec` rate plugs into
    `utils.ledger.compute_deltas`: a fault-path latency regression shows
    up in `cli chaos soak` history exactly like a bench regression.
    `progress`, when given, is called with each ScenarioResult as it
    lands (never-silent soak reporting).  `backend` overrides every
    scenario's declared crypto rung for the whole sweep."""
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        raise KeyError(f"unknown scenarios {unknown}; "
                       f"known: {sorted(SCENARIOS)}")
    if not seeds:
        raise ValueError("empty seed list")
    results: list[ScenarioResult] = []
    agg: dict[str, dict] = {
        n: {"runs": 0, "failures": 0, "breaches": 0,
            "budget_s": SCENARIOS[n].budget_s, "total_duration_s": 0.0,
            "max_duration_s": 0.0, "failed_seeds": [], "breached_seeds": []}
        for n in names}
    for n in names:
        for seed in seeds:
            r = run_scenario(n, seed=seed, artifacts=artifacts,
                             keep_artifacts=keep_artifacts,
                             backend=backend)
            results.append(r)
            a = agg[n]
            a["runs"] += 1
            a["total_duration_s"] += r.duration_s
            a["max_duration_s"] = max(a["max_duration_s"], r.duration_s)
            if not r.ok:
                a["failures"] += 1
                a["failed_seeds"].append(seed)
            if r.budget_breaches:
                a["breaches"] += 1
                a["breached_seeds"].append(seed)
            if progress is not None:
                progress(r)
    configs: dict[str, dict] = {}
    for n, a in agg.items():
        total = a.pop("total_duration_s")
        a["mean_duration_s"] = round(total / a["runs"], 3)
        a["max_duration_s"] = round(a["max_duration_s"], 3)
        # headline rate for ledger.compute_deltas/render_history: a
        # latency regression in the fault path appears as a rate drop
        a["runs_per_sec"] = round(a["runs"] / total, 4) if total > 0 else 0.0
        configs[n] = dict(a)
    summary = {
        "schema": CHAOS_LEDGER_SCHEMA,
        "seeds": [seeds[0], seeds[-1] + 1] if seeds == list(
            range(seeds[0], seeds[-1] + 1)) else list(seeds),
        "n_seeds": len(seeds),
        "configs": configs,
        "total_runs": len(results),
        "total_failures": sum(a["failures"] for a in configs.values()),
        "total_breaches": sum(a["breaches"] for a in configs.values()),
    }
    if ledger_path is not None:
        from tendermint_tpu.utils import ledger as ledgermod
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        for r in results:
            ledgermod.append_entry(ledger_path, {
                "schema": CHAOS_RUN_SCHEMA, "scenario": r.name,
                "seed": r.seed, "ok": r.ok, "backend": r.backend,
                "duration_s": round(r.duration_s, 3),
                "budget_s": r.budget_s,
                "budget_breaches": r.budget_breaches,
                "budget_metrics": _json_safe(r.budget_metrics),
                "event_log_hash": r.event_log_hash,
                "artifact_dir": r.artifact_dir,
                "timestamp": stamp})
        entry = dict(summary)
        entry["timestamp"] = stamp
        prior = [e for e in ledgermod.load(ledger_path)
                 if e.get("schema") == CHAOS_LEDGER_SCHEMA]
        summary["deltas"] = ledgermod.compute_deltas(prior, configs)
        ledgermod.append_entry(ledger_path, entry)
        summary["ledger_path"] = os.path.abspath(ledger_path)
    return {"summary": summary, "results": results}
