"""Seed-deterministic fault-scenario engine.

A *scenario* is a declarative composition of fault injectors (byzantine
vote streams, network partitions, crash-restart storms, device-fault
storms) plus a post-mortem: safety and liveness invariants checked
against flight-recorder and metric evidence after the run.

The replay contract
-------------------
Every scenario runs from ONE integer seed.  All injector randomness is
derived from it through `utils.chaos.derive_seed(seed, *labels)` — the
per-injector RNGs, the `FuzzedConnection` streams, the crash schedule,
the byzantine height sets.  The engine keeps an *event log* with two
streams:

- **plan events** (`ctx.plan(...)`): the injected-fault schedule as
  derived from the seed — which heights equivocate, which window the
  partition covers, which chaos spec the crypto ladder gets, which RNG
  seeds were handed out.  Plan events are a pure function of
  (scenario, seed): their canonical-JSON sha256 is the *event log
  hash*, and two runs with the same seed MUST produce the same hash
  (tier-1 asserts this).
- **notes** (`ctx.note(...)`): what actually happened at runtime
  (timing-dependent: observed heights, breaker trips, eviction order).
  Notes are dumped for triage but never hashed.

Post-mortem + artifacts
-----------------------
After the scenario body returns, the engine runs its registered safety
and liveness invariants.  On ANY failure (body exception or invariant
violation) it dumps a per-scenario artifact directory:

    <artifacts>/<scenario>-seed<N>/
        trace.json      flight-recorder Chrome trace (load in Perfetto)
        metrics.json    phase-labeled REGISTRY snapshots (incl. per-rung
                        crypto counters)
        events.json     the event log: plan stream, hash, and notes
        result.json     manifest: outcome, failures, seed — the replay
                        input for `cli chaos replay`

Triage flow: read result.json for the failed invariant, open trace.json
in Perfetto against events.json's plan timeline, then re-run bit-
identically with `cli chaos run --scenario <name> --seed <N>`.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from tendermint_tpu.utils import chaos as chaosmod
from tendermint_tpu.utils import tracing
from tendermint_tpu.utils.log import get_logger
from tendermint_tpu.utils.metrics import REGISTRY

log = get_logger("scenarios")

# Fixed default seed for the faults tier: CI runs are reproducible by
# default, and a red run's artifact names tell you the seed to replay.
DEFAULT_SEED = 20260806


class InvariantViolation(AssertionError):
    """A scenario post-mortem assertion failed.  The message must carry
    the evidence (heights, hashes, metric values) — it is what lands in
    result.json for triage."""


class EventLog:
    """Deterministic plan stream + timing-dependent note stream."""

    def __init__(self):
        self._plan: list[dict] = []
        self._notes: list[dict] = []

    def plan(self, event: str, **fields) -> None:
        """Record one planned injection.  Fields must be JSON-safe and
        derived only from the seed (never wall-clock) — they are hashed
        into the determinism contract."""
        self._plan.append({"event": event, **fields})

    def note(self, event: str, **fields) -> None:
        """Record a runtime observation (not hashed)."""
        self._notes.append({"t": round(time.time(), 6),
                            "event": event, **fields})

    def hash(self) -> str:
        blob = json.dumps(self._plan, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()

    def to_dict(self) -> dict:
        return {"hash": self.hash(), "plan": list(self._plan),
                "notes": list(self._notes)}


class ScenarioContext:
    """What a scenario body (and its injectors) gets to work with."""

    def __init__(self, scenario: "Scenario", seed: int):
        self.scenario = scenario
        self.seed = seed
        self.log = EventLog()
        self.recorder = tracing.RECORDER
        self.metric_phases: list[dict] = []
        self._rngs: dict[str, object] = {}

    # -- derived randomness ---------------------------------------------
    def derive_seed(self, *labels: str) -> int:
        return chaosmod.derive_seed(self.seed, self.scenario.name, *labels)

    def rng(self, name: str):
        """A named `random.Random` derived from the scenario seed; the
        derivation is logged as a plan event so the seed handed to each
        injector is part of the hashed schedule."""
        if name not in self._rngs:
            import random
            child = self.derive_seed("rng", name)
            self.log.plan("rng", name=name, seed=child)
            self._rngs[name] = random.Random(child)
        return self._rngs[name]

    # -- event log shorthands -------------------------------------------
    def plan(self, event: str, **fields) -> None:
        self.log.plan(event, **fields)

    def note(self, event: str, **fields) -> None:
        self.log.note(event, **fields)

    # -- evidence capture ------------------------------------------------
    def snapshot_metrics(self, phase: str) -> dict:
        """Capture a phase-labeled REGISTRY snapshot (includes the
        rung-labeled crypto counters) — the metric evidence invariants
        assert against."""
        snap = {"phase": phase, "metrics": REGISTRY.snapshot()}
        self.metric_phases.append(snap)
        self.recorder.instant("scenario.phase", phase=phase)
        return snap

    def metrics(self, phase: str) -> dict | None:
        for snap in self.metric_phases:
            if snap["phase"] == phase:
                return snap["metrics"]
        return None


class Scenario:
    """A registered scenario: body + named safety/liveness invariants.

    `body(ctx)` composes injectors and returns a JSON-safe observations
    dict; each invariant is `(name, fn)` with `fn(ctx, obs)` raising
    InvariantViolation on failure.  Every shipped scenario must carry at
    least one safety AND one liveness invariant — registration enforces
    it so a scenario cannot silently ship without a post-mortem."""

    def __init__(self, name: str, description: str, body,
                 safety: list, liveness: list, smoke: bool = False):
        if not safety or not liveness:
            raise ValueError(
                f"scenario {name!r} needs >=1 safety and >=1 liveness "
                f"invariant (got {len(safety)}/{len(liveness)})")
        self.name = name
        self.description = description
        self.body = body
        self.safety = list(safety)
        self.liveness = list(liveness)
        self.smoke = smoke


SCENARIOS: dict[str, Scenario] = {}


def register(name: str, description: str, safety: list, liveness: list,
             smoke: bool = False):
    """Decorator: `@register("byz-equivocation", "...", safety=[...],
    liveness=[...])` over the scenario body."""
    def deco(fn):
        if name in SCENARIOS:
            raise ValueError(f"duplicate scenario {name!r}")
        SCENARIOS[name] = Scenario(name, description, fn,
                                   safety, liveness, smoke=smoke)
        return fn
    return deco


class ScenarioResult:
    def __init__(self, name: str, seed: int, ok: bool, failures: list[str],
                 event_log_hash: str, duration_s: float,
                 observations: dict, artifact_dir: str | None):
        self.name = name
        self.seed = seed
        self.ok = ok
        self.failures = failures
        self.event_log_hash = event_log_hash
        self.duration_s = duration_s
        self.observations = observations
        self.artifact_dir = artifact_dir

    def to_dict(self) -> dict:
        return {"scenario": self.name, "seed": self.seed, "ok": self.ok,
                "failures": self.failures,
                "event_log_hash": self.event_log_hash,
                "duration_s": round(self.duration_s, 3),
                "observations": _json_safe(self.observations),
                "artifact_dir": self.artifact_dir}


def _json_safe(obj):
    """Coerce observation values for the manifest: bytes become hex,
    unknown objects their repr — a dump must never fail the dumper."""
    if isinstance(obj, dict):
        return {str(k): _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (bytes, bytearray)):
        return obj.hex()
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


def artifacts_root(override: str | None = None) -> str:
    return (override or os.environ.get("TM_SCENARIO_ARTIFACTS")
            or os.path.join(os.getcwd(), "chaos_artifacts"))


def _dump_artifacts(ctx: ScenarioContext, result: ScenarioResult,
                    root: str) -> str:
    d = os.path.join(root, f"{ctx.scenario.name}-seed{ctx.seed}")
    os.makedirs(d, exist_ok=True)
    ctx.recorder.dump(os.path.join(d, "trace.json"))
    for fname, payload in (
            ("metrics.json", ctx.metric_phases),
            ("events.json", ctx.log.to_dict()),
            ("result.json", result.to_dict())):
        tmp = os.path.join(d, fname + ".tmp")
        with open(tmp, "w") as f:
            json.dump(_json_safe(payload), f, indent=1)
        os.replace(tmp, os.path.join(d, fname))
    return d


def run_scenario(name: str, seed: int = DEFAULT_SEED,
                 artifacts: str | None = None,
                 keep_artifacts: bool = False) -> ScenarioResult:
    """Run one registered scenario end to end: install the ChaosConfig,
    execute the body, snapshot metrics, run the safety+liveness
    post-mortem, and dump artifacts on failure (always, when
    `keep_artifacts`).  Never raises on scenario failure — the result
    carries the verdict; raises only on unknown scenario names."""
    sc = SCENARIOS.get(name)
    if sc is None:
        raise KeyError(f"unknown scenario {name!r}; "
                       f"known: {sorted(SCENARIOS)}")
    ctx = ScenarioContext(sc, seed)
    ctx.plan("scenario", name=name, seed=seed)
    prev_cfg = chaosmod.install(chaosmod.ChaosConfig(seed=seed))
    failures: list[str] = []
    obs: dict = {}
    t0 = time.perf_counter()
    ctx.snapshot_metrics("start")
    try:
        with ctx.recorder.span("scenario.run", cat=tracing.CAT_NONE,
                               scenario=name, seed=seed):
            try:
                obs = sc.body(ctx) or {}
            except InvariantViolation as e:
                failures.append(f"body: {e}")
            except Exception as e:  # noqa: BLE001 - the post-mortem must
                # still run and the trace must still dump on ANY failure
                log.error("scenario body crashed", scenario=name,
                          error=f"{type(e).__name__}: {e}")
                failures.append(f"body: {type(e).__name__}: {e}")
        ctx.snapshot_metrics("end")
        for kind, invariants in (("safety", sc.safety),
                                 ("liveness", sc.liveness)):
            for inv_name, fn in invariants:
                try:
                    fn(ctx, obs)
                    ctx.note("invariant", name=inv_name, kind=kind,
                             ok=True)
                except AssertionError as e:
                    failures.append(f"{kind}:{inv_name}: {e}")
                    ctx.note("invariant", name=inv_name, kind=kind,
                             ok=False, error=str(e))
                except Exception as e:  # noqa: BLE001 - an invariant that
                    # crashes is a failed invariant, not a passed one
                    failures.append(
                        f"{kind}:{inv_name}: {type(e).__name__}: {e}")
                    ctx.note("invariant", name=inv_name, kind=kind,
                             ok=False, error=f"{type(e).__name__}: {e}")
    finally:
        chaosmod.install(prev_cfg)
    result = ScenarioResult(
        name=name, seed=seed, ok=not failures, failures=failures,
        event_log_hash=ctx.log.hash(),
        duration_s=time.perf_counter() - t0,
        observations=obs, artifact_dir=None)
    if failures or keep_artifacts:
        try:
            result.artifact_dir = _dump_artifacts(
                ctx, result, artifacts_root(artifacts))
            log.info("scenario artifacts dumped", scenario=name,
                     dir=result.artifact_dir)
        except OSError as e:
            log.error("scenario artifact dump failed", scenario=name,
                      error=str(e))
    return result
