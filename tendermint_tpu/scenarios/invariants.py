"""Post-mortem invariant helpers.

Every helper raises `InvariantViolation` whose message carries the
evidence (heights, hashes, metric values) — that message is what the
engine writes into result.json, so a red scenario is triageable without
re-running it.  Scenario bodies stash the raw material (stores, metric
phase labels) in their observations dict; these helpers read it back.
"""

from __future__ import annotations

from tendermint_tpu.scenarios.engine import InvariantViolation


def require(cond: bool, msg: str) -> None:
    if not cond:
        raise InvariantViolation(msg)


# -- safety -----------------------------------------------------------------

def no_conflicting_commits(stores: list, upto: int | None = None) -> None:
    """Agreement: every store that committed height h committed the SAME
    block at h.  The core BFT safety property — two nodes disagreeing on
    any height is consensus failure, whatever else still works."""
    top = min(s.height for s in stores)
    if upto is not None:
        top = min(top, upto)
    for h in range(1, top + 1):
        hashes = {s.load_block(h).hash() for s in stores}
        require(len(hashes) == 1,
                f"conflicting commits at height {h}: "
                f"{sorted(x.hex()[:16] for x in hashes)}")


def prefix_agreement(stores: list) -> None:
    """Agreement over each store's OWN committed prefix: every block a
    store committed matches the block the furthest-ahead store committed
    at that height.  Unlike `no_conflicting_commits` (which only checks
    up to the MINIMUM height), this catches a stale straggler that
    committed a divergent block before falling behind — the live-rig
    shape, where partitioned/crashed nodes legitimately trail the
    quorum but must never disagree with it."""
    ref = max(stores, key=lambda s: s.height)
    for s in stores:
        for h in range(1, s.height + 1):
            got, want = s.load_block(h).hash(), ref.load_block(h).hash()
            require(got == want,
                    f"prefix divergence at height {h}: a node committed "
                    f"{got.hex()[:16]}, the quorum committed "
                    f"{want.hex()[:16]}")


def chains_match(store, ref_store, upto: int) -> None:
    """The synced chain is byte-identical to the honest reference."""
    for h in range(1, upto + 1):
        got, want = store.load_block(h).hash(), ref_store.load_block(h).hash()
        require(got == want,
                f"synced block {h} diverges from honest chain: "
                f"{got.hex()[:16]} != {want.hex()[:16]}")


def metric_increased(ctx, name: str, since: str = "start",
                     until: str = "end") -> int:
    """The metric grew between two phase snapshots; returns the delta.
    The evidence backbone of 'the fault machinery actually fired'."""
    before, after = ctx.metrics(since), ctx.metrics(until)
    require(before is not None and after is not None,
            f"metric phases {since!r}/{until!r} were not snapshotted")
    b, a = before.get(name, 0), after.get(name, 0)
    require(a > b, f"metric {name} did not increase "
                   f"({since}={b} -> {until}={a})")
    return a - b


def no_silent_acceptance(ctx, injected_faults: bool = True) -> None:
    """No silent signature acceptance: every injected device fault was
    SEEN by the supervisor (surfaced as crypto_device_faults and served
    by a fallback rung), never absorbed into an accepted result.  Callers
    pair this with a state-correctness check (chains_match / app hash) —
    together they say 'faults happened, and none leaked into state'."""
    if injected_faults:
        metric_increased(ctx, "crypto_device_faults")
    before, after = ctx.metrics("start"), ctx.metrics("end")
    require(before is not None and after is not None,
            "metric phases start/end missing")
    mm_b = before.get("crypto_spot_check_mismatches", 0)
    mm_a = after.get("crypto_spot_check_mismatches", 0)
    faults_d = (after.get("crypto_device_faults", 0)
                - before.get("crypto_device_faults", 0))
    require(mm_a - mm_b <= faults_d,
            f"spot-check mismatches ({mm_a - mm_b}) not all accounted "
            f"as device faults ({faults_d}) — a wrong answer leaked")


# -- liveness ---------------------------------------------------------------

def height_progressed(label: str, before: int, after: int,
                      min_delta: int) -> None:
    """Height progress resumed after faults cleared: `after` must exceed
    `before` by at least `min_delta` (measured within the scenario's
    deadline — the bound is the scenario's run budget)."""
    require(after - before >= min_delta,
            f"{label}: height only moved {before} -> {after} "
            f"(needed +{min_delta}) after faults cleared")


def completed(obs: dict, key: str, what: str) -> None:
    """The scenario's terminal condition was reached inside its budget
    (obs[key] is set truthy by the body when the deadline was met)."""
    require(bool(obs.get(key)), f"{what} did not complete in budget "
                                f"(observations[{key!r}]={obs.get(key)!r})")
