"""In-process node harnesses the scenario catalog composes.

Three rigs, in increasing realism (mirroring the tiers the test suite
grew organically in `tests/test_consensus.py` / `test_fastsync.py` /
`test_reactor.py` / `test_wal_corruption.py`):

- `wire_net`: N ConsensusStates delivering broadcasts directly to each
  other's feed methods — no transport; the fastest rig for byzantine
  vote-stream scenarios.
- `fastsync_source` / `fastsync_syncer`: real switches + blockchain
  reactors over in-memory pairs; the rig for lying/stale/partial-commit
  peers and device-fault storms during sync.
- `reactor_net`: full consensus+mempool reactors over switches with
  FuzzedConnection wrappers in the conn stack, so partition/delay-storm
  injectors can flip fuzz profiles on live links.
- `solo_node`: a real sqlite-backed Node (WAL on disk) for
  crash-restart storms.
"""

from __future__ import annotations

import time

from tendermint_tpu.blockchain.reactor import BlockchainReactor
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.config import Config, test_config
from tendermint_tpu.consensus import messages as M
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.mempool.mempool import Mempool
from tendermint_tpu.mempool.reactor import MempoolReactor
from tendermint_tpu.p2p.fuzz import FuzzedConnection
from tendermint_tpu.p2p.switch import connect_switches, make_switch
from tendermint_tpu.proxy import ClientCreator
from tendermint_tpu.scenarios import fixtures
from tendermint_tpu.state import execution
from tendermint_tpu.state.state import get_state
from tendermint_tpu.utils.db import MemDB


def wait_until(pred, timeout: float, poll: float = 0.02) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return bool(pred())


# -- wire net (no transport) ------------------------------------------------

class WireNode:
    """ConsensusState + mempool + store, broadcast_cb-wired."""

    def __init__(self, priv, gen, cfg: Config | None = None,
                 app: str = "kvstore", wal_path: str = ""):
        cfg = cfg or test_config()
        self.priv = priv
        st = get_state(MemDB(), gen)
        self.conns = ClientCreator(app).new_app_conns()
        self.mempool = Mempool(self.conns.mempool)
        self.block_store = BlockStore(MemDB())
        self.cs = ConsensusState(cfg.consensus, st, self.conns.consensus,
                                 self.block_store, self.mempool,
                                 priv_validator=priv, wal_path=wal_path)


def wire_net(chain_id: str, n: int, app: str = "kvstore",
             seed: int = 0) -> tuple[list[WireNode], list, object]:
    """N validators wired directly: every broadcast lands in every other
    node's feed methods.  Returns (nodes, privs, genesis)."""
    privs, _vs = fixtures.make_validators(n, seed=seed)
    gen = fixtures.make_genesis(chain_id, privs)
    nodes = [WireNode(p, gen, app=app) for p in privs]

    def make_cb(me: WireNode):
        def cb(msg):
            for other in nodes:
                if other is me:
                    continue
                if isinstance(msg, M.VoteMessage):
                    other.cs.add_vote(msg.vote, peer_id="net")
                elif isinstance(msg, M.ProposalMessage):
                    other.cs.set_proposal(msg.proposal, peer_id="net")
                elif isinstance(msg, M.BlockPartMessage):
                    other.cs.add_proposal_block_part(
                        msg.height, msg.round, msg.part, peer_id="net")
        return cb

    for nd in nodes:
        nd.cs.broadcast_cb = make_cb(nd)
    return nodes, privs, gen


# -- fast-sync rig ----------------------------------------------------------

def fastsync_source(chain_id: str, chain, gen, moniker: str = "source"):
    """A served chain: store + state advanced to the tip, behind a
    switch.  Returns (switch, state, store)."""
    state = get_state(MemDB(), gen)
    conns = ClientCreator("kvstore").new_app_conns()
    store = BlockStore(MemDB())
    for block, ps, seen in chain:
        store.save_block(block, ps, seen)
        execution.apply_block(state, None, conns.consensus, block,
                              ps.header, execution.MockMempool(),
                              check_last_commit=False)
    reactor = BlockchainReactor(state, conns.consensus, store,
                                fast_sync=False)
    sw = make_switch(chain_id, {"blockchain": reactor}, moniker=moniker)
    return sw, state, store


def fastsync_syncer(chain_id: str, gen, batch_size: int = 8):
    """A fresh syncing node.  Returns (switch, bc_reactor, cons_reactor,
    store)."""
    state = get_state(MemDB(), gen)
    conns = ClientCreator("kvstore").new_app_conns()
    store = BlockStore(MemDB())
    mp = Mempool(conns.mempool)
    cs = ConsensusState(test_config().consensus, state.copy(),
                        conns.consensus, store, mp)
    cons_reactor = ConsensusReactor(cs, fast_sync=True)
    bc_reactor = BlockchainReactor(state, conns.consensus, store,
                                   fast_sync=True, batch_size=batch_size)
    bc_reactor.on_caught_up = cons_reactor.switch_to_consensus
    sw = make_switch(chain_id, {"blockchain": bc_reactor,
                                "consensus": cons_reactor},
                     moniker="syncer")
    return sw, bc_reactor, cons_reactor, store


# -- reactor net (real p2p, fuzz wrappers in the stack) ---------------------

class ReactorNode:
    """Consensus core + reactors + switch (the gossip-only rig)."""

    def __init__(self, priv, gen, chain_id: str, moniker: str,
                 cfg: Config | None = None, fuzz: bool = False):
        cfg = cfg or test_config()
        cfg.p2p.laddr = ""        # in-memory pairs only, no TCP listener
        if fuzz:
            # wrappers with zero probabilities: inert until an injector
            # flips a profile (partition/delay storm)
            cfg.p2p.fuzz = True
            cfg.p2p.fuzz_drop_prob = 0.0
            cfg.p2p.fuzz_delay_prob = 0.0
        st = get_state(MemDB(), gen)
        self.conns = ClientCreator("kvstore").new_app_conns()
        self.mempool = Mempool(self.conns.mempool)
        self.block_store = BlockStore(MemDB())
        self.cs = ConsensusState(cfg.consensus, st, self.conns.consensus,
                                 self.block_store, self.mempool,
                                 priv_validator=priv)
        self.cons_reactor = ConsensusReactor(self.cs)
        self.mp_reactor = MempoolReactor(self.mempool)
        self.switch = make_switch(chain_id, {
            "consensus": self.cons_reactor,
            "mempool": self.mp_reactor,
        }, config=cfg.p2p, moniker=moniker)

    def fuzz_links(self) -> list[FuzzedConnection]:
        """The FuzzedConnection wrapper of every live peer link on this
        node's side (empty when fuzz=False)."""
        out = []
        for peer in self.switch.peers():
            sec = peer.mconn.conn
            inner = getattr(sec, "_conn", None)
            if isinstance(inner, FuzzedConnection):
                out.append(inner)
        return out

    def start(self):
        self.switch.start()

    def stop(self):
        self.switch.stop()


def reactor_net(chain_id: str, n: int, fuzz: bool = False,
                seed: int = 0) -> tuple[list[ReactorNode], list]:
    privs, _vs = fixtures.make_validators(n, seed=seed)
    gen = fixtures.make_genesis(chain_id, privs)
    nodes = [ReactorNode(privs[i], gen, chain_id, f"node{i}", fuzz=fuzz)
             for i in range(n)]
    for nd in nodes:
        nd.start()
    for i in range(n):
        for j in range(i + 1, n):
            connect_switches(nodes[i].switch, nodes[j].switch)
    return nodes, privs


# -- full node (sqlite home, WAL on disk) -----------------------------------

def solo_node(home: str, chain_id: str, pv_key_byte: int = 0x31):
    """A real single-validator Node over a sqlite home dir — the rig for
    crash-restart storms (its consensus WAL lives on disk at
    <home>/data/cs.wal).  Rebuilding with the same args after a crash is
    the restart."""
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.types import (GenesisDoc, GenesisValidator, PrivKey,
                                      PrivValidator)
    cfg = test_config()
    cfg.base.home = home
    cfg.base.db_backend = "sqlite"
    cfg.rpc.laddr = ""
    cfg.p2p.laddr = ""
    pv = PrivValidator(PrivKey(bytes([pv_key_byte]) * 32))
    gen = GenesisDoc(chain_id=chain_id,
                     validators=[GenesisValidator(pv.pub_key.bytes_, 10)],
                     genesis_time_ns=1)
    return Node(cfg, priv_validator=pv, genesis_doc=gen)
