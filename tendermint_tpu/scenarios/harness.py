"""In-process node harnesses the scenario catalog composes.

Three rigs, in increasing realism (mirroring the tiers the test suite
grew organically in `tests/test_consensus.py` / `test_fastsync.py` /
`test_reactor.py` / `test_wal_corruption.py`):

- `wire_net`: N ConsensusStates delivering broadcasts directly to each
  other's feed methods — no transport; the fastest rig for byzantine
  vote-stream scenarios.
- `fastsync_source` / `fastsync_syncer`: real switches + blockchain
  reactors over in-memory pairs; the rig for lying/stale/partial-commit
  peers and device-fault storms during sync.
- `reactor_net`: full consensus+mempool reactors over switches with
  FuzzedConnection wrappers in the conn stack, so partition/delay-storm
  injectors can flip fuzz profiles on live links.
- `solo_node`: a real sqlite-backed Node (WAL on disk) for
  crash-restart storms.
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.blockchain.reactor import BlockchainReactor
from tendermint_tpu.blockchain.store import BlockStore
from tendermint_tpu.config import Config, test_config
from tendermint_tpu.consensus import messages as M
from tendermint_tpu.consensus.reactor import ConsensusReactor
from tendermint_tpu.consensus.state import ConsensusState
from tendermint_tpu.mempool.mempool import Mempool
from tendermint_tpu.mempool.reactor import MempoolReactor
from tendermint_tpu.p2p.fuzz import FuzzedConnection
from tendermint_tpu.p2p.switch import connect_switches, make_switch
from tendermint_tpu.proxy import ClientCreator
from tendermint_tpu.scenarios import fixtures
from tendermint_tpu.state import execution
from tendermint_tpu.state.state import get_state
from tendermint_tpu.utils import tracing
from tendermint_tpu.utils.db import MemDB
from tendermint_tpu.utils.metrics import Histogram


def wait_until(pred, timeout: float, poll: float = 0.02) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(poll)
    return bool(pred())


# -- wire net (no transport) ------------------------------------------------

class WireNode:
    """ConsensusState + mempool + store, broadcast_cb-wired.

    `state`, `conns` and `block_store` are injectable so a restart rig
    (WireMesh) can rebuild a node over a retained block store with an
    app replayed back to the crash height."""

    def __init__(self, priv, gen, cfg: Config | None = None,
                 app: str = "kvstore", wal_path: str = "",
                 state=None, conns=None, block_store=None,
                 node_id: str = ""):
        cfg = cfg or test_config()
        self.priv = priv
        st = state if state is not None else get_state(MemDB(), gen)
        self.conns = conns or ClientCreator(app).new_app_conns()
        self.mempool = Mempool(self.conns.mempool)
        self.block_store = (block_store if block_store is not None
                            else BlockStore(MemDB()))
        self.cs = ConsensusState(cfg.consensus, st, self.conns.consensus,
                                 self.block_store, self.mempool,
                                 priv_validator=priv, wal_path=wal_path,
                                 node_id=node_id)


def wire_net(chain_id: str, n: int, app: str = "kvstore",
             seed: int = 0) -> tuple[list[WireNode], list, object]:
    """N validators wired directly: every broadcast lands in every other
    node's feed methods.  Returns (nodes, privs, genesis)."""
    privs, _vs = fixtures.make_validators(n, seed=seed)
    gen = fixtures.make_genesis(chain_id, privs)
    nodes = [WireNode(p, gen, app=app) for p in privs]

    def make_cb(me: WireNode):
        def cb(msg):
            for other in nodes:
                if other is me:
                    continue
                if isinstance(msg, M.VoteMessage):
                    other.cs.add_vote(msg.vote, peer_id="net")
                elif isinstance(msg, M.ProposalMessage):
                    other.cs.set_proposal(msg.proposal, peer_id="net")
                elif isinstance(msg, M.BlockPartMessage):
                    other.cs.add_proposal_block_part(
                        msg.height, msg.round, msg.part, peer_id="net")
        return cb

    for nd in nodes:
        nd.cs.broadcast_cb = make_cb(nd)
    return nodes, privs, gen


def start_wire_net(nodes: list[WireNode], stagger_s: float = 0.0) -> None:
    """Start every WireNode's consensus state, optionally staggered —
    late starters model operators bringing a big net up one node at a
    time; rounds must still converge once +2/3 are live."""
    for i, nd in enumerate(nodes):
        nd.cs.start()
        if stagger_s > 0.0 and i < len(nodes) - 1:
            time.sleep(stagger_s)


class WireMesh:
    """Partitionable wire mesh: the 50-100 validator live-consensus rig.

    Same no-transport delivery as `wire_net`, with the link matrix made
    explicit so chaos schedules can cut/heal node pairs and
    crash/restart nodes mid-round:

    - `isolate(victims)` cuts every victim<->survivor link (the victims
      keep talking among themselves — an island partition); `heal()`
      restores the full mesh.
    - `crash(i)` stops a node's consensus thread; `restart(i)` rebuilds
      it over its RETAINED block store, replaying the committed prefix
      through a fresh app so state/app stay consistent.

    Wire delivery has no catchup gossip: a node that misses commits
    while down or severed stays permanently behind the quorum (votes
    for heights it has not reached are dropped).  Scenario invariants
    must therefore assert QUORUM liveness plus committed-prefix
    agreement, and adversary schedules must keep >=2/3 of the voting
    power live and connected.

    A sampler thread timestamps every height the live quorum commits,
    so scenarios can assert metric budgets (commit latency percentiles)
    instead of only wall-clock.
    """

    def __init__(self, chain_id: str, n: int, seed: int = 0,
                 timeouts: dict[str, float] | None = None,
                 app: str = "kvstore"):
        self.chain_id = chain_id
        self.n = n
        self.app = app
        self._timeouts = timeouts
        self.privs, _vs = fixtures.make_validators(n, seed=seed)
        self.gen = fixtures.make_genesis(chain_id, self.privs)
        self._lock = threading.Lock()
        self._down: set[int] = set()
        self._cut: set[frozenset[int]] = set()
        self.store_dbs = [MemDB() for _ in range(n)]
        self.nodes: list[WireNode] = [self._build(i) for i in range(n)]
        for i in range(n):
            self.nodes[i].cs.broadcast_cb = self._make_cb(i)
        self.restarts = 0
        # one report per restart(): {"node", "replay_blocks", "replay_s"}
        # — scenario notes cite these instead of re-deriving them, and
        # the snapshot-join budget compares them against restore+tail
        self.restart_reports: list[dict] = []
        self._last_replay = (0, 0.0)
        self._samples: list[tuple[int, float]] = []   # (height, t_seen)
        self._sampler: threading.Thread | None = None
        self._sampler_stop = threading.Event()
        # -- timeline plane (telemetry/) --
        # per-node height lifecycle records delivered by the commit_cb
        # hook at the COMMIT SITE — the exact-timestamp source the 50ms
        # poll sampler above only approximates
        self.lifecycle_records: list[dict] = []
        self._commit_stamps: dict[int, float] = {}  # height -> first commit
        # per-run gossip fan-out lag (send stamp -> delivery), kept
        # mesh-local so sequential scenario runs in one process don't
        # read each other through the global REGISTRY
        self.gossip_hist = Histogram(Histogram.LATENCY_BOUNDS)
        # (i, j) -> [count, sum_s, max_s]; each key is written only by
        # sender i's consensus thread, so per-op GIL atomicity suffices
        self._link_stats: dict[tuple[int, int], list] = {}

    # -- construction / restart ----------------------------------------

    def _build(self, i: int) -> WireNode:
        """(Re)build node `i` over its retained block store.  The app
        conns are fresh, so the committed prefix is replayed through
        them — a from-disk restart without WAL, driven by the store."""
        store = BlockStore(self.store_dbs[i])
        st = get_state(MemDB(), self.gen)
        conns = ClientCreator(self.app).new_app_conns()
        t0 = time.time()
        replayed = 0
        for h in range(store.base, store.height + 1):
            block = store.load_block(h)
            meta = store.load_block_meta(h)
            execution.apply_block(st, None, conns.consensus, block,
                                  meta.block_id.parts,
                                  execution.MockMempool(),
                                  check_last_commit=False)
            replayed += 1
        self._last_replay = (replayed, time.time() - t0)
        node = WireNode(self.privs[i], self.gen,
                        cfg=config_with_timeouts(self._timeouts),
                        app=self.app, state=st, conns=conns,
                        block_store=store, node_id=f"n{i}")
        node.cs.commit_cb = self._on_lifecycle   # survives restarts
        return node

    def _on_lifecycle(self, rec: dict) -> None:
        """commit_cb from every node: ring the record into the mesh's
        merged timeline and stamp the height's FIRST commit — the
        commit-site timestamps commit_latencies() prefers over the poll
        sampler."""
        with self._lock:
            self.lifecycle_records.append(rec)
            h, t = rec["height"], rec["t_commit"]
            cur = self._commit_stamps.get(h)
            if cur is None or t < cur:
                self._commit_stamps[h] = t

    def _make_cb(self, me_i: int):
        def cb(msg):
            with self._lock:
                if me_i in self._down:
                    return
                down = set(self._down)
                cut = set(self._cut)
                nodes = list(self.nodes)
            # origin send stamp: one per broadcast, so every link's lag
            # includes the sender-loop serialization ahead of it — the
            # fan-out cost the gossip_fanout_p99 budget grades
            t0 = tracing.now_epoch()
            stats = self._link_stats
            for j, other in enumerate(nodes):
                if j == me_i or j in down:
                    continue
                if frozenset((me_i, j)) in cut:
                    continue
                if isinstance(msg, M.VoteMessage):
                    other.cs.add_vote(msg.vote, peer_id="net", sent_ts=t0)
                elif isinstance(msg, M.ProposalMessage):
                    other.cs.set_proposal(msg.proposal, peer_id="net",
                                          sent_ts=t0)
                elif isinstance(msg, M.BlockPartMessage):
                    other.cs.add_proposal_block_part(
                        msg.height, msg.round, msg.part, peer_id="net",
                        sent_ts=t0)
                else:
                    continue
                lag = tracing.now_epoch() - t0
                self.gossip_hist.observe(lag)
                st = stats.get((me_i, j))
                if st is None:
                    st = stats[(me_i, j)] = [0, 0.0, 0.0]
                st[0] += 1
                st[1] += lag
                if lag > st[2]:
                    st[2] = lag
        return cb

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        for nd in self.nodes:
            nd.cs.start()

    def stop(self) -> None:
        self.stop_sampler()
        with self._lock:
            self._down.update(range(self.n))
        for nd in self.nodes:
            nd.cs.stop()

    def crash(self, i: int) -> None:
        """SIGKILL-shaped: mark the node dead FIRST (so no sender can
        block on its dead queue), then stop its consensus thread."""
        with self._lock:
            self._down.add(i)
        self.nodes[i].cs.stop()

    def restart(self, i: int) -> None:
        node = self._build(i)
        node.cs.broadcast_cb = self._make_cb(i)
        with self._lock:
            self.nodes[i] = node
            self._down.discard(i)
        node.cs.start()
        self.restarts += 1
        replayed, dt = self._last_replay
        self.restart_reports.append({"node": i,
                                     "replay_blocks": replayed,
                                     "replay_s": round(dt, 4)})

    # -- partitions -----------------------------------------------------

    def isolate(self, victims: list[int]) -> None:
        vs = set(victims)
        with self._lock:
            for v in vs:
                for j in range(self.n):
                    if j not in vs:
                        self._cut.add(frozenset((v, j)))

    def heal(self) -> None:
        with self._lock:
            self._cut.clear()

    # -- observation ----------------------------------------------------

    def live(self) -> list[int]:
        with self._lock:
            return [i for i in range(self.n) if i not in self._down]

    def stores(self) -> list:
        return [nd.block_store for nd in self.nodes]

    def quorum_height(self) -> int:
        """Max committed height across live nodes (0 when all down)."""
        with self._lock:
            nodes = [nd for i, nd in enumerate(self.nodes)
                     if i not in self._down]
        return max((nd.block_store.height for nd in nodes), default=0)

    def start_sampler(self, poll_s: float = 0.05) -> None:
        def run():
            last_h = self.quorum_height()
            while not self._sampler_stop.is_set():
                h = self.quorum_height()
                if h > last_h:
                    now = time.time()
                    for hh in range(last_h + 1, h + 1):
                        self._samples.append((hh, now))
                    last_h = h
                time.sleep(poll_s)
        self._sampler_stop.clear()
        self._sampler = threading.Thread(target=run, daemon=True,
                                         name="wiremesh-sampler")
        self._sampler.start()

    def stop_sampler(self) -> None:
        self._sampler_stop.set()
        if self._sampler is not None:
            self._sampler.join(timeout=5)
            self._sampler = None

    def commit_latencies(self) -> list[float]:
        """Gaps between consecutive commits (seconds), from the
        commit-site stamps the nodes' commit_cb hooks deliver — exact,
        not quantized to the sampler's 50ms poll.  Falls back to the
        poll samples when no hook fired (e.g. a rig built before
        start(), or every node crashed pre-commit)."""
        with self._lock:
            stamps = dict(self._commit_stamps)
        if stamps:
            ts = [stamps[h] for h in sorted(stamps)]
        else:
            ts = [t for _h, t in self._samples]
        return [b - a for a, b in zip(ts, ts[1:])]

    def commit_latency_p99(self) -> float | None:
        gaps = sorted(self.commit_latencies())
        if not gaps:
            return None
        return gaps[min(len(gaps) - 1, int(0.99 * len(gaps)))]

    def timeline_records(self) -> list[dict]:
        """Per-node height lifecycle records (see ConsensusState
        STAGE_NAMES) accumulated by the commit hooks — the mesh
        collector's in-process input."""
        with self._lock:
            return list(self.lifecycle_records)

    def gossip_stats(self) -> dict:
        """Mesh-wide gossip fan-out aggregates.  `per_receiver_wait_s`
        divides the total per-delivery lag by the fan-out degree — the
        serialized gossip wait ONE receiver experienced over the run,
        commensurate with per-node wall clock (the doctor's gossip_delay
        thief).  `worst_link` is the (sender, receiver) pair with the
        largest single-delivery lag."""
        with self._lock:
            links = {k: list(v) for k, v in self._link_stats.items()}
        count = sum(v[0] for v in links.values())
        total = sum(v[1] for v in links.values())
        worst = max(links.items(), key=lambda kv: kv[1][2], default=None)
        return {
            "count": count,
            "total_s": total,
            "mean_s": total / count if count else 0.0,
            "p50": self.gossip_hist.quantile(0.50),
            "p99": self.gossip_hist.quantile(0.99),
            "max_s": worst[1][2] if worst else 0.0,
            "worst_link": list(worst[0]) if worst else None,
            "per_receiver_wait_s": total / max(self.n - 1, 1),
        }


# -- fast-sync rig ----------------------------------------------------------

def fastsync_source(chain_id: str, chain, gen, moniker: str = "source",
                    config=None, app="kvstore"):
    """A served chain: store + state advanced to the tip, behind a
    switch.  Returns (switch, state, store).  Pass a P2PConfig with a
    TCP `laddr` to make the source dialable (the rig for persistent-
    peer reconnect scenarios).  Pass an Application instance as `app`
    to keep a handle on the served app — the snapshot rigs do, so the
    source can also serve snapshots of its state."""
    state = get_state(MemDB(), gen)
    conns = ClientCreator(app).new_app_conns()
    store = BlockStore(MemDB())
    for block, ps, seen in chain:
        store.save_block(block, ps, seen)
        execution.apply_block(state, None, conns.consensus, block,
                              ps.header, execution.MockMempool(),
                              check_last_commit=False)
    reactor = BlockchainReactor(state, conns.consensus, store,
                                fast_sync=False)
    sw = make_switch(chain_id, {"blockchain": reactor}, config=config,
                     moniker=moniker)
    return sw, state, store


def fastsync_syncer(chain_id: str, gen, batch_size: int = 8,
                    fuzz: bool = False, state=None, store=None,
                    app="kvstore"):
    """A fresh syncing node.  Returns (switch, bc_reactor, cons_reactor,
    store).  With `fuzz=True` every link gets an inert FuzzedConnection
    wrapper (zero probabilities) so partition injectors can sever
    individual source links mid-sync.

    `state`/`store`/`app` are injectable for the snapshot-join rig: a
    snapshot-restored State + a `bootstrap()`ed store + the restored
    Application instance make this node sync only the short tail
    `snapshot_height -> tip` instead of the whole chain."""
    state = state if state is not None else get_state(MemDB(), gen)
    conns = ClientCreator(app).new_app_conns()
    store = store if store is not None else BlockStore(MemDB())
    mp = Mempool(conns.mempool)
    cs = ConsensusState(test_config().consensus, state.copy(),
                        conns.consensus, store, mp)
    cons_reactor = ConsensusReactor(cs, fast_sync=True)
    bc_reactor = BlockchainReactor(state, conns.consensus, store,
                                   fast_sync=True, batch_size=batch_size)
    bc_reactor.on_caught_up = cons_reactor.switch_to_consensus
    p2p_cfg = None
    if fuzz:
        p2p_cfg = test_config().p2p
        p2p_cfg.laddr = ""
        p2p_cfg.fuzz = True
        p2p_cfg.fuzz_drop_prob = 0.0
        p2p_cfg.fuzz_delay_prob = 0.0
    sw = make_switch(chain_id, {"blockchain": bc_reactor,
                                "consensus": cons_reactor},
                     config=p2p_cfg, moniker="syncer")
    return sw, bc_reactor, cons_reactor, store


def fuzz_link_to(switch, peer_id: str) -> FuzzedConnection | None:
    """The FuzzedConnection wrapping `switch`'s link to `peer_id`, or
    None when the peer is absent or the link is unfuzzed — the handle
    for asymmetric partitions that sever ONE link of a multi-peer
    switch while the others keep flowing."""
    for peer in switch.peers():
        if peer.id != peer_id:
            continue
        inner = getattr(peer.mconn.conn, "_conn", None)
        if isinstance(inner, FuzzedConnection):
            return inner
    return None


# -- reactor net (real p2p, fuzz wrappers in the stack) ---------------------

class ReactorNode:
    """Consensus core + reactors + switch (the gossip-only rig)."""

    def __init__(self, priv, gen, chain_id: str, moniker: str,
                 cfg: Config | None = None, fuzz: bool = False):
        cfg = cfg or test_config()
        # kept for crash-restart rigs: rebuilding a node from genesis
        # needs (priv, gen, chain_id) back
        self.priv = priv
        self.gen = gen
        cfg.p2p.laddr = ""        # in-memory pairs only, no TCP listener
        if fuzz:
            # wrappers with zero probabilities: inert until an injector
            # flips a profile (partition/delay storm)
            cfg.p2p.fuzz = True
            cfg.p2p.fuzz_drop_prob = 0.0
            cfg.p2p.fuzz_delay_prob = 0.0
        st = get_state(MemDB(), gen)
        self.conns = ClientCreator("kvstore").new_app_conns()
        self.mempool = Mempool(self.conns.mempool)
        self.block_store = BlockStore(MemDB())
        self.cs = ConsensusState(cfg.consensus, st, self.conns.consensus,
                                 self.block_store, self.mempool,
                                 priv_validator=priv)
        self.cons_reactor = ConsensusReactor(self.cs)
        self.mp_reactor = MempoolReactor(self.mempool)
        self.switch = make_switch(chain_id, {
            "consensus": self.cons_reactor,
            "mempool": self.mp_reactor,
        }, config=cfg.p2p, moniker=moniker)

    def fuzz_links(self) -> list[FuzzedConnection]:
        """The FuzzedConnection wrapper of every live peer link on this
        node's side (empty when fuzz=False)."""
        out = []
        for peer in self.switch.peers():
            sec = peer.mconn.conn
            inner = getattr(sec, "_conn", None)
            if isinstance(inner, FuzzedConnection):
                out.append(inner)
        return out

    def start(self):
        self.switch.start()

    def stop(self):
        self.switch.stop()


def config_with_timeouts(timeouts: dict[str, float] | None) -> Config:
    """test_config with consensus timeouts overridden.  The defaults
    (20-100ms) are tuned for <=5-node rigs; a 10+ node net on pure-python
    crypto needs propose/prevote windows that cover its verify load or
    every height burns rounds on timeouts."""
    cfg = test_config()
    for k, v in (timeouts or {}).items():
        if not hasattr(cfg.consensus, k):
            raise ValueError(f"unknown consensus timeout field {k!r}")
        setattr(cfg.consensus, k, v)
    return cfg


def start_reactor_net(nodes: list[ReactorNode],
                      stagger_s: float = 0.0) -> None:
    """Rolling bring-up of a reactor net: each node starts, meshes with
    the already-live prefix, and (optionally) the next waits stagger_s —
    a 10-50 node net coming up one operator at a time."""
    for i, nd in enumerate(nodes):
        nd.start()
        for j in range(i):
            connect_switches(nodes[j].switch, nd.switch)
        if stagger_s > 0.0 and i < len(nodes) - 1:
            time.sleep(stagger_s)


def reactor_net(chain_id: str, n: int, fuzz: bool = False,
                seed: int = 0, stagger_s: float = 0.0,
                profiles: dict[int, dict] | None = None,
                timeouts: dict[str, float] | None = None,
                autostart: bool = True,
                ) -> tuple[list[ReactorNode], list]:
    """Full-mesh reactor net, sized for 10-50 validator rigs.

    `stagger_s` sleeps between node bring-ups (each node connects to the
    already-started prefix as it comes up), modeling a rolling start of
    a big net.  `profiles` maps node index -> fuzz profile fields
    (see FuzzedConnection.set_profile) applied to that node's links once
    the mesh is wired — per-node fault profiles, e.g. one flaky-link
    node in an otherwise clean net.  Profiles need `fuzz=True`.
    `timeouts` overrides consensus timeouts on every node (see
    config_with_timeouts).  `autostart=False` returns the net built but
    not started, so injector hooks can install before height 1."""
    if profiles and not fuzz:
        raise ValueError("per-node fault profiles need fuzz=True "
                         "(no FuzzedConnection wrappers to flip otherwise)")
    bad = [i for i in (profiles or {}) if not 0 <= i < n]
    if bad:
        raise ValueError(f"profile indices {bad} out of range for n={n}")
    privs, _vs = fixtures.make_validators(n, seed=seed)
    gen = fixtures.make_genesis(chain_id, privs)
    nodes = [ReactorNode(privs[i], gen, chain_id, f"node{i}",
                         cfg=config_with_timeouts(timeouts), fuzz=fuzz)
             for i in range(n)]
    if autostart:
        start_reactor_net(nodes, stagger_s=stagger_s)
        for idx, prof in (profiles or {}).items():
            for link in nodes[idx].fuzz_links():
                link.set_profile(**prof)
    elif profiles:
        raise ValueError("profiles need autostart=True (links exist only "
                         "after the mesh is wired); apply them after "
                         "start_reactor_net instead")
    return nodes, privs


# -- full node (sqlite home, WAL on disk) -----------------------------------

def solo_node(home: str, chain_id: str, pv_key_byte: int = 0x31):
    """A real single-validator Node over a sqlite home dir — the rig for
    crash-restart storms (its consensus WAL lives on disk at
    <home>/data/cs.wal).  Rebuilding with the same args after a crash is
    the restart."""
    from tendermint_tpu.node.node import Node
    from tendermint_tpu.types import (GenesisDoc, GenesisValidator, PrivKey,
                                      PrivValidator)
    cfg = test_config()
    cfg.base.home = home
    cfg.base.db_backend = "sqlite"
    cfg.rpc.laddr = ""
    cfg.p2p.laddr = ""
    pv = PrivValidator(PrivKey(bytes([pv_key_byte]) * 32))
    gen = GenesisDoc(chain_id=chain_id,
                     validators=[GenesisValidator(pv.pub_key.bytes_, 10)],
                     genesis_time_ns=1)
    return Node(cfg, priv_validator=pv, genesis_doc=gen)
