"""Seeded mempool flood generator: the ≥100k tx/s abuse profile.

Drives mixed valid / bad-signature / duplicate / low-priority traffic
through the RPC `broadcast_tx_sync` handler (the same code path a
public node exposes) into the admission controller and the batch
plane.  The corpus is built once from the scenario RNG — signing is
front-loaded so the submit loop measures ADMISSION capacity, not
signing capacity — and every submission is classified into exactly one
outcome from the RPC response, giving the zero-silent-drops accounting
the eviction-storm scenario audits:

    offered == admitted + dup + full + backpressure + bad_sig
               + encoding + app + errors

Kinds in a corpus (weights per `Mix`):

- ``unsigned``: unique raw payloads (priority 0) — the cheap bulk
  traffic that fills and then bounces off a capped pool
- ``signed``: unique ed25519 envelopes with seeded priorities — the
  traffic that exercises the batch-plane verify lane and priority
  eviction
- ``bad_sig``: signed envelopes with one corrupted signature byte —
  must die at the verify gate, never reach the app
- ``dup``: verbatim resubmissions of earlier corpus entries — must die
  in the dedup cache in O(1)

Throughput note (1-vCPU tier-1 rig): the rejection paths this floods
are 1.4–4 µs each, so a single submit thread sustains >150k/s; workers
default low because more GIL-sharing threads only add contention.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from tendermint_tpu.abci.types import (ERR_BAD_SIG, ERR_ENCODING,
                                       ERR_MEMPOOL_FULL, OK)
from tendermint_tpu.mempool.mempool import sign_tx_ed25519

OUTCOMES = ("admitted", "dup", "full", "backpressure", "bad_sig",
            "encoding", "app", "error")


@dataclass
class Mix:
    """Corpus composition.  Counts are absolute (the corpus is finite
    and cycled by the submit loop, so effective traffic shares follow
    these proportions)."""
    unsigned: int = 6_000
    signed: int = 256
    bad_sig: int = 64
    dup_frac: float = 0.25      # fraction of corpus repeated verbatim
    payload_bytes: int = 64
    priorities: tuple = (0, 1, 2, 5, 9)   # sampled per signed tx


@dataclass
class LoadReport:
    offered: int = 0
    duration_s: float = 0.0
    outcomes: dict = field(default_factory=dict)

    @property
    def offered_per_sec(self) -> float:
        return self.offered / max(self.duration_s, 1e-9)

    def summary(self) -> dict:
        return {"offered": self.offered,
                "duration_s": round(self.duration_s, 3),
                "offered_per_sec": round(self.offered_per_sec, 1),
                "outcomes": dict(self.outcomes)}


def build_corpus(rng, mix: Mix | None = None) -> list[dict]:
    """Pre-built `broadcast_tx_*` params dicts, seed-deterministic in
    content AND order.  Signing happens here, once, so the flood loop
    never pays for it."""
    mix = mix or Mix()
    entries: list[dict] = []
    for i in range(mix.unsigned):
        payload = b"lg-u%08d-" % i + rng.randbytes(
            max(mix.payload_bytes - 14, 0))
        entries.append({"tx": payload.hex()})
    for i in range(mix.signed):
        seed = rng.randbytes(32)
        prio = rng.choice(mix.priorities)
        payload = b"lg-s%08d-" % i + rng.randbytes(
            max(mix.payload_bytes - 14, 0))
        entries.append({"tx": sign_tx_ed25519(seed, payload,
                                              priority=prio).hex()})
    for i in range(mix.bad_sig):
        seed = rng.randbytes(32)
        payload = b"lg-b%08d-" % i + rng.randbytes(
            max(mix.payload_bytes - 14, 0))
        tx = bytearray(sign_tx_ed25519(seed, payload,
                                       priority=rng.choice(mix.priorities)))
        tx[40] ^= 0x01               # corrupt one signature byte
        entries.append({"tx": bytes(tx).hex()})
    rng.shuffle(entries)
    n_dup = int(len(entries) * mix.dup_frac)
    entries += [entries[rng.randrange(len(entries))]
                for _ in range(n_dup)]
    rng.shuffle(entries)
    return entries


def classify(call, params: dict) -> str:
    """Submit one tx through an RPC broadcast handler and name its
    outcome.  `call` is a routes handler (e.g. broadcast_tx_sync)."""
    try:
        res = call(params)
    except ValueError:
        return "dup"                 # broadcast_tx_sync's cache-hit shape
    except Exception:
        return "error"
    code = res.get("code", OK)
    if code == OK:
        return "admitted"
    if code == ERR_MEMPOOL_FULL:
        return ("backpressure"
                if "backpressure" in res.get("log", "") else "full")
    if code == ERR_BAD_SIG:
        return "bad_sig"
    if code == ERR_ENCODING:
        return "encoding"
    return "app"


class LoadGen:
    """Closed-loop flood: N workers cycle a pre-built corpus through a
    submit callable as fast as the interpreter allows, for a fixed
    duration.  Totals are merged post-join — no shared hot-path state
    beyond the mempool's own locks."""

    def __init__(self, call, corpus: list[dict], workers: int = 1):
        self.call = call
        self.corpus = corpus
        self.workers = max(workers, 1)

    def _run_worker(self, wid: int, stop_at: float,
                    out: list) -> None:
        call = self.call
        corpus = self.corpus
        n = len(corpus)
        counts = dict.fromkeys(OUTCOMES, 0)
        offered = 0
        i = (wid * n) // self.workers
        perf = time.perf_counter
        while perf() < stop_at:
            counts[classify(call, corpus[i])] += 1
            offered += 1
            i += 1
            if i == n:
                i = 0
        out[wid] = (offered, counts)

    def run(self, duration_s: float) -> LoadReport:
        out: list = [None] * self.workers
        t0 = time.perf_counter()
        stop_at = t0 + duration_s
        threads = [threading.Thread(target=self._run_worker,
                                    args=(w, stop_at, out), daemon=True)
                   for w in range(self.workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        report = LoadReport(duration_s=elapsed,
                            outcomes=dict.fromkeys(OUTCOMES, 0))
        for offered, counts in out:
            report.offered += offered
            for k, v in counts.items():
                report.outcomes[k] += v
        return report
