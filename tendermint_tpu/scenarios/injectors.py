"""Composable fault injectors.

Each injector derives its decisions from a ctx-provided RNG and records
them as plan events (hashed into the determinism contract) before any
runtime effect; what actually lands is recorded as notes.  Injectors
never reach for wall-clock randomness — the whole point is that the
same seed replays the same fault schedule.
"""

from __future__ import annotations

import os
import struct

from tendermint_tpu.blockchain import messages as BM
from tendermint_tpu.blockchain.reactor import BLOCKCHAIN_CHANNEL
from tendermint_tpu.consensus import messages as M
from tendermint_tpu.consensus.wal import REC_MESSAGE
from tendermint_tpu.p2p.fuzz import FuzzedConnection
from tendermint_tpu.types import (TYPE_PREVOTE, Vote, ZERO_BLOCK_ID)
from tendermint_tpu.types.block import Block
from tendermint_tpu.types.vote import DuplicateVoteEvidence


def plan_heights(ctx, name: str, lo: int, hi: int, k: int) -> list[int]:
    """Pick k distinct target heights in [lo, hi] from the scenario seed
    and log them as the injection schedule."""
    rng = ctx.rng(name)
    span = list(range(lo, hi + 1))
    rng.shuffle(span)
    heights = sorted(span[:k])
    ctx.plan(name, heights=heights)
    return heights


# -- byzantine vote streams -------------------------------------------------

def equivocate(ctx, node, priv, chain_id: str, heights: list[int],
               broadcast=None) -> None:
    """Make `node` double-sign: for every prevote at a scheduled height
    it also signs a conflicting nil prevote with the raw key (bypassing
    the PrivValidator HRS guard, like the reference's
    ByzantinePrivValidator) and broadcasts it.  `broadcast` defaults to
    the node's own broadcast_cb (wire nets)."""
    targets = set(heights)
    orig_sign_add = node.cs._sign_add_vote
    send = broadcast or (lambda msg: node.cs.broadcast_cb(msg))

    def equivocating_sign_add(type_, block_id):
        orig_sign_add(type_, block_id)
        if (type_ != TYPE_PREVOTE or block_id.is_zero()
                or node.cs.height not in targets):
            return
        idx = node.cs.validators.index_of(priv.address)
        v = Vote(validator_address=priv.address, validator_index=idx,
                 height=node.cs.height, round=node.cs.round, type=type_,
                 block_id=ZERO_BLOCK_ID)
        sig = priv.priv_key.sign(v.sign_bytes(chain_id))
        v = Vote(**{**v.__dict__, "signature": sig})
        ctx.note("equivocation.sent", height=v.height, round=v.round)
        send(M.VoteMessage(v))

    node.cs._sign_add_vote = equivocating_sign_add


def fabricate_evidence(ctx, privs, vs, chain_id: str, n_real: int,
                       n_bogus: int) -> tuple[list, list]:
    """Evidence-flood ammunition: `n_real` valid equivocation proofs by
    in-set validators, and `n_bogus` invalid ones (stranger validators,
    agreeing votes, torn signatures) that a sound pool must refuse.
    Returns (real, bogus)."""
    from tendermint_tpu.types import BlockID, PrivKey, PrivValidator

    rng = ctx.rng("evidence")

    def conflicting_pair(priv, height, in_set: bool):
        idx = vs.index_of(priv.address) if in_set else 0
        bid = BlockID(bytes([rng.randrange(1, 256)]) * 32)

        def signed(block_id):
            v = Vote(validator_address=priv.address, validator_index=idx,
                     height=height, round=0, type=TYPE_PREVOTE,
                     block_id=block_id)
            sig = priv.priv_key.sign(v.sign_bytes(chain_id))
            return Vote(**{**v.__dict__, "signature": sig})
        return signed(bid), signed(ZERO_BLOCK_ID)

    real = []
    for i in range(n_real):
        priv = privs[rng.randrange(len(privs))]
        a, b = conflicting_pair(priv, height=1 + i, in_set=True)
        real.append(DuplicateVoteEvidence(a, b))

    bogus = []
    for i in range(n_bogus):
        kind = rng.randrange(3)
        if kind == 0:                       # stranger: not in the set
            stranger = PrivValidator(
                PrivKey(bytes([200 + i % 50, rng.randrange(256)])
                        + b"\x00" * 30))
            a, b = conflicting_pair(stranger, height=1 + i, in_set=False)
            bogus.append(DuplicateVoteEvidence(a, b))
        elif kind == 1:                     # agreement: no equivocation
            priv = privs[rng.randrange(len(privs))]
            a, _ = conflicting_pair(priv, height=1 + i, in_set=True)
            bogus.append(DuplicateVoteEvidence(a, a))
        else:                               # torn signature
            priv = privs[rng.randrange(len(privs))]
            a, b = conflicting_pair(priv, height=1 + i, in_set=True)
            bad = Vote(**{**b.__dict__,
                          "signature": bytes(64)})
            bogus.append(DuplicateVoteEvidence(a, bad))
    ctx.plan("evidence-flood", n_real=n_real, n_bogus=n_bogus)
    return real, bogus


# -- byzantine fast-sync peers ----------------------------------------------

def tamper_block_server(ctx, switch, chain, mode: str,
                        heights: list[int]) -> None:
    """Turn a fastsync_source switch into a byzantine peer that answers
    BlockRequests for scheduled heights with replayed commits:

    - mode="stale": block h is served with the commit of an OLDER height
      spliced in as its last_commit — a stale finality proof (the PoTE
      adversary: yesterday's proof re-presented for today's block)
    - mode="partial": block h's last_commit is pruned to a single
      precommit, far below +2/3 — a partial-commit replay (the ACE
      adversary: a quorum certificate missing most of its power)

    `chain` is the fixture list [(block, part_set, seen_commit)]."""
    if mode not in ("stale", "partial"):
        raise ValueError(f"unknown tamper mode {mode!r}")
    targets = set(heights)
    ctx.plan("tamper-server", mode=mode, heights=sorted(targets))
    reactor = switch.reactor("blockchain")
    orig_receive = reactor.receive

    def evil_last_commit(height: int):
        block = chain[height - 1][0]
        lc = block.last_commit
        if mode == "stale":
            # the seen-commit of an older block: valid signatures, wrong
            # block — exactly what a replayed finality proof looks like
            older = max(height - 3, 1)
            return chain[older - 1][2]
        keep = [v if i == 0 else None for i, v in enumerate(lc.precommits)]
        return type(lc)(block_id=lc.block_id, precommits=keep)

    def tampering_receive(ch_id, peer, raw):
        msg = BM.decode_msg(raw)
        if isinstance(msg, BM.BlockRequest) and msg.height in targets \
                and msg.height > 1:
            block = chain[msg.height - 1][0]
            evil = Block(header=block.header, txs=block.txs,
                         last_commit=evil_last_commit(msg.height))
            ctx.note("tamper.served", height=msg.height, mode=mode)
            peer.try_send(BLOCKCHAIN_CHANNEL,
                          BM.encode_msg(BM.BlockResponse(evil.encode())))
            return
        orig_receive(ch_id, peer, raw)

    reactor.receive = tampering_receive


# -- network faults ---------------------------------------------------------

def sever_inbound(ctx, links: list[FuzzedConnection],
                  stall: float = 1.0, label: str = "") -> None:
    """Partition one direction: every read on these links stalls, so the
    owner stops hearing the network while its own frames still flow.
    Heal with `restore`.  Stalling (not dropping) keeps the
    SecretConnection frame sequence intact, so the link survives the
    partition and resumes cleanly."""
    ctx.note("partition.sever", links=len(links), label=label)
    for fc in links:
        fc.set_profile(read_drop_prob=1.0, read_stall=stall)


def delay_storm(ctx, links: list[FuzzedConnection], delay_prob: float,
                max_delay: float, label: str = "") -> None:
    """Reordering/jitter storm: both directions of these links delay a
    fraction of operations (message reordering across channels follows
    from unequal per-frame delays)."""
    ctx.note("storm.start", links=len(links), delay_prob=delay_prob,
             max_delay=max_delay, label=label)
    for fc in links:
        fc.set_profile(read_delay_prob=delay_prob,
                       write_delay_prob=delay_prob, max_delay=max_delay)


def restore(ctx, links: list[FuzzedConnection], label: str = "") -> None:
    """Heal: zero every fault probability on these links."""
    ctx.note("partition.heal", links=len(links), label=label)
    for fc in links:
        fc.set_profile(read_drop_prob=0.0, read_delay_prob=0.0,
                       write_drop_prob=0.0, write_delay_prob=0.0)


# -- crash-restart ----------------------------------------------------------

def tear_wal_tail(ctx, path: str, rng) -> int:
    """Simulate SIGKILL mid-record-write: append a torn frame — a valid
    header promising `length` bytes followed by only part of the body —
    exactly the on-disk state of a writer killed between write() calls.
    Half the time the existing tail is also cut mid-frame (the page-
    cache variant).  Returns the torn-frame offset."""
    payload = bytes(rng.randrange(256) for _ in range(24))
    body = struct.pack(">B", REC_MESSAGE) + payload
    cut = rng.randrange(1, len(body))
    size = os.path.getsize(path)
    variant = rng.randrange(2)
    with open(path, "r+b") as f:
        if variant and size > 12:
            # cut the last few bytes of the real tail first
            f.truncate(size - rng.randrange(1, 8))
        f.seek(0, os.SEEK_END)
        off = f.tell()
        f.write(struct.pack(">II", len(body), 0xDEADBEEF) + body[:cut])
        f.flush()
        os.fsync(f.fileno())
    ctx.note("wal.torn", path=path, offset=off, cut=cut, variant=variant)
    return off
