"""Live-consensus big rigs: 50-100 validators committing heights while
a combined adversary fires.

These are the scale tier of the scenario catalogue: a full WireMesh of
ConsensusStates (scenarios/harness.py) must keep committing while
partitions isolate a minority island, nodes crash and restart from
their own committed prefix, one validator equivocates, and the
scenario's supervised crypto ladder walks a demote/recover cycle.

Two properties of the wire rig shape every invariant here:

- No catchup gossip: a node that misses commits while severed or down
  stays permanently behind the quorum.  Liveness is therefore asserted
  for the QUORUM (the live, connected, current majority), and safety as
  committed-prefix agreement across every store — stale nodes may
  trail, but may never disagree.
- Adversary sizing keeps >2/3 of voting power live and connected at
  all times (partition + crash + byzantine counts are chosen so the
  remaining current voters clear the quorum threshold with margin).

Alongside the wall-clock budget, each rig declares METRIC budgets —
commit latency p99 (timestamped at the commit site by the lifecycle
hook, with the 50ms poll sampler as fallback), rounds-per-height
(round churn from stale proposers and partition waves), ladder
demotion count, and stage-level timeline budgets (prevote-quorum p99
and gossip fan-out p99 from the merged telemetry timeline) — checked
by the engine as first-class invariants and ledgered per-seed.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from tendermint_tpu.scenarios import harness, injectors
from tendermint_tpu.scenarios import invariants as inv
from tendermint_tpu.scenarios.engine import register
from tendermint_tpu.utils import chaos as chaosmod
from tendermint_tpu.utils.metrics import REGISTRY

# a big net on memoized pure-python crypto commits a height in ~1.5s
# (n=50) / ~4s (n=100); propose windows must cover a full height of
# GIL-shared work plus scheduler jitter or every height burns rounds
LIVE_TIMEOUTS_50 = {
    "timeout_propose": 5.0, "timeout_propose_delta": 1.5,
    "timeout_prevote": 2.5, "timeout_prevote_delta": 0.75,
    "timeout_precommit": 2.5, "timeout_precommit_delta": 0.75,
}
LIVE_TIMEOUTS_100 = {
    "timeout_propose": 8.0, "timeout_propose_delta": 2.0,
    "timeout_prevote": 4.0, "timeout_prevote_delta": 1.0,
    "timeout_precommit": 4.0, "timeout_precommit_delta": 1.0,
}


def _walk_ladder(ctx) -> None:
    """Demote and recover the scenario's supervised ladder while the
    mesh keeps committing: install a raise-mode crypto chaos spec,
    probe `verify_batch` until the breaker trips, clear the storm, and
    probe until the half-open path recovers the rung.  The consensus
    hot path is untouched (scalar vote verifies go through
    types/keys.py, and micro-batching only engages on a device rung) —
    the leg proves the ladder machinery stays live UNDER the rig load,
    and feeds the ladder_demotions budget metric."""
    be = ctx.backend
    if be is None or not hasattr(be, "_rungs"):
        ctx.note("live.rungwalk-skipped", reason="scalar backend")
        return
    from tendermint_tpu.crypto import pure_ed25519 as ref
    trips0 = REGISTRY.crypto_breaker_trips.value
    recov0 = REGISTRY.crypto_breaker_recoveries.value
    chaosmod.install(chaosmod.ChaosConfig(seed=ctx.seed,
                                          crypto="raise:every=1"))
    be.chaos = chaosmod.CryptoChaos.current()
    seed32 = bytes(32)
    pub = np.frombuffer(ref.pubkey_from_seed(seed32), np.uint8)
    msg = np.zeros(32, np.uint8)
    sig = np.frombuffer(ref.sign(seed32, msg.tobytes()), np.uint8)
    deadline = time.time() + 20
    while (REGISTRY.crypto_breaker_trips.value == trips0
           and time.time() < deadline):
        be.verify_batch(pub[None, :], msg[None, :], sig[None, :])
        time.sleep(0.02)
    be.chaos.active = False
    ctx.note("live.chaos-cleared",
             tripped=REGISTRY.crypto_breaker_trips.value > trips0)
    deadline = time.time() + 15
    while (REGISTRY.crypto_breaker_recoveries.value == recov0
           and time.time() < deadline):
        be.verify_batch(pub[None, :], msg[None, :], sig[None, :])
        time.sleep(0.05)
    ctx.note("live.rungwalk-done",
             trips=REGISTRY.crypto_breaker_trips.value - trips0,
             recoveries=REGISTRY.crypto_breaker_recoveries.value - recov0)


def _live_rounds_body(ctx, *, n: int, net_seed: int, target_heights: int,
                      timeouts: dict, partition_count: int,
                      crash_count: int, equivocations: int,
                      window_s: float, target_timeout_s: float):
    chain_id = f"chaos-live-{n}"
    rng = ctx.rng("live-adversary")
    # disjoint adversary cast, seed-derived and hash-logged: a replay on
    # the same seed partitions the same nodes
    idxs = list(range(n))
    rng.shuffle(idxs)
    victims = sorted(idxs[:partition_count])
    crash_targets = sorted(idxs[partition_count:
                                partition_count + crash_count])
    byz_i = (idxs[partition_count + crash_count]
             if equivocations else None)
    ctx.plan("adversary-cast", victims=victims, crashes=crash_targets,
             byz=byz_i, window_s=window_s)

    mesh = harness.WireMesh(chain_id, n, seed=net_seed, timeouts=timeouts)
    evidence: list = []
    ev_lock = threading.Lock()
    if byz_i is not None:
        heights = injectors.plan_heights(ctx, "equivocation", 2,
                                         target_heights, k=equivocations)
        injectors.equivocate(ctx, mesh.nodes[byz_i], mesh.privs[byz_i],
                             chain_id, heights)
        for i, nd in enumerate(mesh.nodes):
            if i != byz_i:
                nd.cs.evsw.subscribe(
                    "scenario", "EvidenceDoubleSign",
                    lambda e: (ev_lock.acquire(), evidence.append(e),
                               ev_lock.release()))
    rounds0 = REGISTRY.rounds_started.value
    trips0 = REGISTRY.crypto_breaker_trips.value
    mesh.start()
    mesh.start_sampler()
    try:
        base_ok = harness.wait_until(lambda: mesh.quorum_height() >= 2,
                                     timeout=120)
        ctx.snapshot_metrics("converged")

        def partition_leg():
            mesh.isolate(victims)
            ctx.note("live.partitioned", victims=victims)
            time.sleep(window_s)
            mesh.heal()
            ctx.note("live.healed")

        def crash_leg():
            # quick cycles: mark-dead -> stop -> rebuild over the
            # retained store (replaying the committed prefix through a
            # fresh app); a restart that misses a height goes stale,
            # which the sizing absorbs
            for i in crash_targets:
                mesh.crash(i)
                ctx.note("live.crashed", node=i)
                time.sleep(0.5)
                mesh.restart(i)
                ctx.note("live.restarted", node=i,
                         height=mesh.nodes[i].block_store.height)

        sched = ctx.schedule("live-adversary")
        sched.add("partition", partition_leg, after=0.5, jitter_s=1.0)
        if crash_targets:
            sched.add("crash-restart", crash_leg, after=1.5, jitter_s=1.0)
        sched.add("rung-walk", lambda: _walk_ladder(ctx),
                  after=0.2, jitter_s=0.5)
        sched.run(join_timeout_s=120.0)

        reached = harness.wait_until(
            lambda: mesh.quorum_height() >= target_heights,
            timeout=target_timeout_s)
        quorum_h = mesh.quorum_height()
        total_height_gain = sum(s.height for s in mesh.stores())
        stores = mesh.stores()
    finally:
        mesh.stop()
    rounds_delta = REGISTRY.rounds_started.value - rounds0
    demotions = REGISTRY.crypto_breaker_trips.value - trips0
    p99 = mesh.commit_latency_p99()
    with ev_lock:
        ev_count = len(evidence)
    budget_metrics = {
        "rounds_per_height": round(
            rounds_delta / max(total_height_gain, 1), 3),
        "ladder_demotions": demotions,
    }
    # no samples means no observed commits: leave the metric out so the
    # budget check reports it missing instead of grading a placeholder
    if p99 is not None:
        budget_metrics["commit_latency_p99"] = round(p99, 3)
    # stage-level budgets from the mesh's merged timeline (telemetry/):
    # p99 duration of each quorum stage across every (node, height) and
    # the gossip fan-out p99 across every delivery.  Same omit-if-empty
    # rule as commit_latency_p99 — the engine grades MISSING as a
    # breach, so a rig that never committed reads red, not green.
    from tendermint_tpu import telemetry
    timeline = telemetry.collect_mesh(mesh)
    telemetry.feed_registry(timeline)
    stats = timeline["stage_stats"]
    if stats.get("prevote", {}).get("count"):
        budget_metrics["prevote_quorum_p99"] = round(
            stats["prevote"]["p99"], 3)
        budget_metrics["precommit_quorum_p99"] = round(
            stats["precommit"]["p99"], 3)
    gossip = timeline["gossip"]
    if gossip.get("count"):
        budget_metrics["gossip_fanout_p99"] = round(gossip["p99"], 4)
    doctor = telemetry.consensus_doctor(timeline)
    ctx.note("live.timeline", heights=len(timeline["heights"]),
             nodes=len(timeline["nodes"]),
             largest_thief=doctor["largest_thief"],
             sums_to_wall=doctor["sums_to_wall"],
             commit_spread_p99=round(telemetry.collector.percentile(
                 [h["commit_spread_s"] for h in timeline["heights"]],
                 0.99), 4))
    ctx.note("live.result", quorum_height=quorum_h,
             target=target_heights, rounds_delta=rounds_delta,
             total_height_gain=total_height_gain,
             evidence=ev_count, restarts=mesh.restarts,
             heights=[s.height for s in stores],
             **budget_metrics)
    return {"base_ok": base_ok, "reached": reached,
            "quorum_height": quorum_h, "target_heights": target_heights,
            "byz": byz_i is not None, "evidence_count": ev_count,
            "restarts": mesh.restarts,
            "budget_metrics": budget_metrics,
            "_stores": stores}


def _live_safety_agreement(ctx, obs):
    inv.prefix_agreement(obs["_stores"])


def _live_safety_evidence(ctx, obs):
    if obs["byz"]:
        inv.require(obs["evidence_count"] >= 1,
                    "the equivocating validator ran unobserved — no "
                    "DuplicateVoteEvidence captured by any honest node")


def _live_liveness(ctx, obs):
    inv.completed(obs, "base_ok", "initial convergence of the mesh")
    inv.completed(
        obs, "reached",
        f"quorum commit progress under the combined adversary "
        f"(reached {obs['quorum_height']}, "
        f"needed {obs['target_heights']})")


def _live_liveness_ladder(ctx, obs):
    inv.metric_increased(ctx, "crypto_breaker_trips")
    inv.metric_increased(ctx, "crypto_breaker_recoveries")


register(
    "live-rounds-50",
    "50-validator live wire mesh under a COMBINED adversary: an 8-node "
    "minority island partition, a crash-restart that replays its own "
    "committed prefix, one equivocating validator, and a supervised "
    "ladder demote/recover walk; the quorum commits 10+ heights with "
    "prefix agreement everywhere, within commit-latency and "
    "round-churn budgets",
    safety=[("prefix-agreement", _live_safety_agreement),
            ("equivocation-evidenced", _live_safety_evidence)],
    liveness=[("quorum-commits-heights", _live_liveness),
              ("ladder-walked", _live_liveness_ladder)],
    smoke=False, budget_s=420.0, backend="rig",
    budgets={"commit_latency_p99": {"max": 30.0},
             "rounds_per_height": {"max": 3.0},
             "ladder_demotions": {"max": 50},
             # stage-level budgets (telemetry/): a prevote stage is
             # bounded by the same round-churn ceiling as commit
             # latency; gossip fan-out is in-process queue handoff, so
             # seconds of lag means the sender loop starved under GIL
             "prevote_quorum_p99": {"max": 30.0},
             "gossip_fanout_p99": {"max": 5.0}})(
    lambda ctx: _live_rounds_body(
        ctx, n=50, net_seed=5, target_heights=10,
        timeouts=LIVE_TIMEOUTS_50, partition_count=8, crash_count=1,
        equivocations=2, window_s=8.0, target_timeout_s=240.0))


register(
    "live-rounds-100-chaos",
    "100-validator live wire mesh under the heaviest combined "
    "adversary: a 15-node island partition, two crash-restarts, an "
    "equivocating validator, and a ladder demote/recover walk; the "
    "quorum still commits 6+ heights with prefix agreement and metric "
    "budgets held",
    safety=[("prefix-agreement", _live_safety_agreement),
            ("equivocation-evidenced", _live_safety_evidence)],
    liveness=[("quorum-commits-heights", _live_liveness),
              ("ladder-walked", _live_liveness_ladder)],
    smoke=False, budget_s=600.0, backend="rig",
    budgets={"commit_latency_p99": {"max": 60.0},
             "rounds_per_height": {"max": 4.0},
             "ladder_demotions": {"max": 50},
             "prevote_quorum_p99": {"max": 60.0},
             "gossip_fanout_p99": {"max": 10.0}})(
    lambda ctx: _live_rounds_body(
        ctx, n=100, net_seed=5, target_heights=6,
        timeouts=LIVE_TIMEOUTS_100, partition_count=15, crash_count=2,
        equivocations=2, window_s=10.0, target_timeout_s=300.0))
