"""Mempool ingress overload scenarios: the 100k tx/s flood gate and
the priority-eviction audit.

ROADMAP item 3's acceptance bar, pointed at the admission controller
in `mempool/mempool.py`:

- `mempool-flood` (stress, rig tier): a seeded `scenarios/loadgen.py`
  flood drives >=100k txs/s of mixed valid / bad-sig / duplicate /
  low-priority traffic through the RPC `broadcast_tx_sync` handler
  into a live 4-validator WireMesh node, with admission p50/p99
  latency and the rig's `commit_latency_p99` declared as metric
  budgets — consensus must keep committing WHILE the front door sheds
  an order of magnitude more traffic than the pool can hold.
- `eviction-storm` (smoke, tier-1 adjacent): a capped standalone pool
  under a mixed-priority storm must evict lowest-priority-oldest
  first with ZERO priority inversions, account every submission in
  exactly one outcome (zero silent drops — every rejection lands in
  `mempool_rejected{reason}`, every eviction in `mempool_evicted`),
  drop evicted hashes from the dedup cache so resubmission works, and
  journal evictions so a crash + `recover_wal` resurrects exactly the
  surviving set.

Both scenarios observe admission latency through bucket DELTAS of the
`mempool_admit_seconds` histogram, so a nightly process that ran other
scenarios first cannot pollute the quantiles.
"""

from __future__ import annotations

import os
import tempfile
import types

from tendermint_tpu.config import MempoolConfig, test_config
from tendermint_tpu.mempool.mempool import Mempool, sign_tx_ed25519
from tendermint_tpu.proxy import ClientCreator
from tendermint_tpu.rpc.routes import Routes
from tendermint_tpu.scenarios import harness, loadgen
from tendermint_tpu.scenarios import invariants as inv
from tendermint_tpu.scenarios.engine import register
from tendermint_tpu.utils.metrics import REGISTRY

# commit work per height is bounded so the 1-vCPU rig spends its GIL
# slices on admission + consensus instead of giant DeliverTx sweeps
# (a commit is 4 in-process nodes each verifying + delivering the
# block, so every 128 block-txs costs the flood workers real GIL time)
FLOOD_BLOCK_TXS = 96
FLOOD_TIMEOUTS = {
    "timeout_propose": 3.0, "timeout_propose_delta": 1.0,
    "timeout_prevote": 1.5, "timeout_prevote_delta": 0.5,
    "timeout_precommit": 1.5, "timeout_precommit_delta": 0.5,
    # a 3s inter-height rest (test_config skips it by default): the rig
    # stays live under flood without the GIL spending most of its
    # slices on back-to-back commits
    "timeout_commit": 3.0, "skip_timeout_commit": 0,
}


def _rpc_for(mempool) -> Routes:
    """A Routes table over a stub node: the scenarios exercise the real
    RPC broadcast handlers (parse, check_tx, result shaping) without
    paying for a full Node."""
    node = types.SimpleNamespace(config=test_config(), mempool=mempool,
                                 switch=None)
    return Routes(node)


def _admit_buckets():
    return REGISTRY.mempool_admit_seconds.buckets()


def _delta_quantile(before, after, q: float) -> float:
    """q-quantile of the admissions observed BETWEEN two cumulative
    bucket snapshots (same interpolation as Histogram.quantile)."""
    total = after[-1][1] - before[-1][1]
    if total <= 0:
        return 0.0
    target = q * total
    lo, prev = 0.0, 0
    top = after[-2][0] if len(after) > 1 else after[-1][0]
    for (le, c1), (_, c0) in zip(after, before):
        cum = c1 - c0
        if cum >= target and cum > prev:
            if le == float("inf"):
                return top
            return lo + (le - lo) * (target - prev) / (cum - prev)
        if le != float("inf"):
            lo = le
        prev = cum
    return top


def _rejected_total() -> int:
    return sum(v for _, v in REGISTRY.mempool_rejected.items())


def _evicted_total() -> int:
    return sum(v for _, v in REGISTRY.mempool_evicted.items())


# -- mempool-flood ---------------------------------------------------------

def _flood_body(ctx):
    rng = ctx.rng("flood")
    mesh = harness.WireMesh("chaos-mempool-flood", 4, seed=7,
                            timeouts=FLOOD_TIMEOUTS)
    for nd in mesh.nodes:
        nd.cs.cfg.max_block_size_txs = FLOOD_BLOCK_TXS
    target = mesh.nodes[0].mempool
    # overload knobs: a pool two orders of magnitude smaller than the
    # offered traffic, and a backpressure trigger of ONE pending verify
    # lane — on a 1-vCPU rig a single in-flight mempool-class verify IS
    # plane saturation, and shedding signature floods before the verify
    # (not after) is exactly what keeps the front door at 100k+/s while
    # each verify costs tens of ms
    target.max_txs = 1_000
    target.max_bytes = 2_000_000
    target.backpressure_lanes = 1
    call = _rpc_for(target).broadcast_tx_sync
    # bulk traffic is unsigned priority-0 (the O(1) full-shed path);
    # signed/bad-sig lanes are present but RARE: every pure-python
    # verify the plane accepts holds the GIL ~10ms, so a dense signed
    # slice keeps one verify perpetually in flight and taxes the cheap
    # shed paths ~50%.  A sparse slice (bad-sig entries still re-verify
    # every cycle — rejection pops them from the dedup cache) exercises
    # verify/evict/backpressure while leaving the plane mostly idle
    corpus = loadgen.build_corpus(
        rng, loadgen.Mix(unsigned=30_000, signed=4, bad_sig=2,
                         dup_frac=0.15))
    ctx.plan("flood.rig", validators=4, corpus=len(corpus),
             max_txs=target.max_txs,
             backpressure_lanes=target.backpressure_lanes)

    rejected0, evicted0 = _rejected_total(), _evicted_total()
    mesh.start()
    mesh.start_sampler()
    try:
        base_ok = harness.wait_until(lambda: mesh.quorum_height() >= 2,
                                     timeout=120)
        h0 = mesh.quorum_height()
        # launch the flood on the heels of a fresh commit so its window
        # opens in the inter-height gap rather than mid-commit
        harness.wait_until(lambda: mesh.quorum_height() > h0, timeout=60)
        h0 = mesh.quorum_height()
        ctx.snapshot_metrics("preflood")
        b0 = _admit_buckets()
        # 3 workers: the GIL serializes the cheap reject paths anyway
        # (more pumping threads only thrash), but the plane keeps ~one
        # signed verify in flight at all times, pinning ~one worker —
        # two spares keep the shed paths saturated through those stalls
        # 6s spans two full commit cadences, so offered/s averages over
        # the commit GIL bursts instead of riding one good/bad alignment.
        # 2 workers: the GIL serializes the shed path, so extra pumping
        # threads only add switch thrash — the second worker exists to
        # keep pumping through the (rare) verify stalls of the first
        report = loadgen.LoadGen(call, corpus, workers=2).run(
            duration_s=6.0)
        b1 = _admit_buckets()
        ctx.snapshot_metrics("postflood")
        # the rig must still be making progress: two more quorum
        # heights on top of wherever the flood found it
        alive = harness.wait_until(
            lambda: mesh.quorum_height() >= h0 + 2, timeout=120)
        h1 = mesh.quorum_height()
    finally:
        mesh.stop()
    p50 = _delta_quantile(b0, b1, 0.50)
    p99 = _delta_quantile(b0, b1, 0.99)
    commit_p99 = mesh.commit_latency_p99()
    rejected_d = _rejected_total() - rejected0
    evicted_d = _evicted_total() - evicted0
    budget_metrics = {
        "offered_per_sec": round(report.offered_per_sec, 1),
        "admit_p50_s": round(p50, 6),
        "admit_p99_s": round(p99, 6),
        "backpressure_rejections": report.outcomes["backpressure"],
    }
    if commit_p99 is not None:
        budget_metrics["commit_latency_p99"] = round(commit_p99, 3)
    ctx.note("flood.result", heights=(h0, h1), evicted=evicted_d,
             rejected=rejected_d, offered=report.offered,
             duration_s=round(report.duration_s, 3),
             outcomes=dict(report.outcomes), **budget_metrics)
    return {"base_ok": base_ok, "alive": alive, "h0": h0, "h1": h1,
            "offered": report.offered, "outcomes": report.outcomes,
            "rejected_delta": rejected_d, "evicted_delta": evicted_d,
            "budget_metrics": budget_metrics}


def _flood_safety_accounting(ctx, obs):
    out = obs["outcomes"]
    inv.require(out["error"] == 0,
                f"{out['error']} submissions raised instead of "
                f"returning a typed outcome")
    inv.require(sum(out.values()) == obs["offered"],
                "loadgen outcome buckets do not sum to offered load")
    # every non-admitted submission must land in mempool_rejected:
    # admitted txs may additionally be evicted later, but a rejection
    # that the counters never saw is a silent drop
    not_admitted = obs["offered"] - out["admitted"]
    inv.require(obs["rejected_delta"] == not_admitted,
                f"mempool_rejected moved {obs['rejected_delta']} for "
                f"{not_admitted} non-admitted submissions — "
                f"silent drops")


def _flood_safety_overload_modes(ctx, obs):
    out = obs["outcomes"]
    inv.require(out["full"] > 0,
                "the flood never hit the full-pool rejection path — "
                "not an overload run")
    inv.require(out["bad_sig"] > 0,
                "no bad-signature rejections: the verify gate went "
                "unexercised")
    inv.require(out["dup"] > 0,
                "no duplicate rejections: the dedup cache went "
                "unexercised")
    inv.require(obs["evicted_delta"] > 0,
                "no priority evictions: the flood never displaced a "
                "lower-priority tx")


def _flood_liveness_rig(ctx, obs):
    inv.completed(obs, "base_ok", "initial convergence of the mesh")
    inv.completed(obs, "alive",
                  f"quorum progress under flood (reached {obs['h1']}, "
                  f"needed {obs['h0'] + 2})")


def _flood_liveness_offered(ctx, obs):
    inv.require(obs["offered"] > 0, "loadgen offered no traffic")


register(
    "mempool-flood",
    "a seeded loadgen drives >=100k tx/s of mixed valid/bad-sig/dup/"
    "low-priority traffic through the RPC broadcast path into one "
    "node of a live 4-validator WireMesh: admission sheds the "
    "overload through typed ERR_MEMPOOL_FULL rejections, priority "
    "eviction and reject-before-verify backpressure, within admission "
    "p50/p99 latency budgets, while the rig keeps committing inside "
    "its commit_latency_p99 budget",
    safety=[("zero-silent-drops", _flood_safety_accounting),
            ("all-overload-modes-exercised", _flood_safety_overload_modes)],
    liveness=[("rig-commits-through-flood", _flood_liveness_rig),
              ("flood-ran", _flood_liveness_offered)],
    smoke=False, budget_s=420.0, backend="rig",
    budgets={"offered_per_sec": {"min": 100_000},
             "admit_p50_s": {"max": 0.001},
             "admit_p99_s": {"max": 0.25},
             "backpressure_rejections": {"min": 1},
             "commit_latency_p99": {"max": 30.0}})(_flood_body)


# -- eviction-storm --------------------------------------------------------

STORM_POOL = 64          # pool cap: small enough to storm in seconds
STORM_FILL_PRIOS = (1, 2, 3, 4, 5)
STORM_PRIOS = (0, 1, 2, 3, 4, 5, 6, 7, 8, 9)


def _storm_body(ctx):
    rng = ctx.rng("storm")
    wal_dir = tempfile.mkdtemp(prefix="eviction-storm-")
    wal_path = os.path.join(wal_dir, "mempool.wal")
    cfg = MempoolConfig(max_txs=STORM_POOL, backpressure_lanes=0)
    conns = ClientCreator("kvstore").new_app_conns()
    mp = Mempool(conns.mempool, cfg, wal_path=wal_path)
    call = _rpc_for(mp).broadcast_tx_sync

    evict_log: list = []     # (victim tx, victim prio, survivor floor)
    inversions = [0]

    def on_evict(h, tx, prio):
        # fired under the pool lock: _tx_prio is exactly the survivor
        # set (victims of a multi-eviction still pending count as
        # survivors — if one of THEM ranks below this victim, that is
        # a real inversion too)
        floor = min(mp._tx_prio.values(), default=None)
        evict_log.append((tx, prio, floor))
        if floor is not None and prio > floor:
            inversions[0] += 1

    mp.on_evict = on_evict
    rejected0, evicted0 = _rejected_total(), _evicted_total()
    b0 = _admit_buckets()
    outcomes = dict.fromkeys(loadgen.OUTCOMES, 0)

    def submit(tx: bytes) -> str:
        k = loadgen.classify(call, {"tx": tx.hex()})
        outcomes[k] += 1
        return k

    # -- phase 1: fill the pool to its cap with mid-priority txs ------
    fill = [sign_tx_ed25519(rng.randbytes(32), b"fill-%03d" % i,
                            priority=rng.choice(STORM_FILL_PRIOS))
            for i in range(STORM_POOL)]
    for tx in fill:
        submit(tx)
    filled = mp.size()
    ctx.plan("storm.filled", size=filled, cap=STORM_POOL)

    # -- phase 2: the storm — mixed priorities against a full pool ----
    storm = [sign_tx_ed25519(rng.randbytes(32), b"storm-%03d" % i,
                             priority=rng.choice(STORM_PRIOS))
             for i in range(160)]
    for tx in storm:
        submit(tx)
    evicted_txs = [tx for tx, _, _ in evict_log]
    ctx.note("storm.stormed", evictions=len(evict_log),
             size=mp.size(), inversions=inversions[0])

    # -- phase 3: crash + recover — the journal must hold exactly the
    # surviving set, never an evicted tx (no close(): a crash doesn't
    # flush politely) --------------------------------------------------
    survivors = {h for h, _, _ in mp.txs_with_heights()}
    conns2 = ClientCreator("kvstore").new_app_conns()
    mp2 = Mempool(conns2.mempool, cfg, wal_path=wal_path)
    recovered_n = mp2.recover_wal()
    recovered = {h for h, _, _ in mp2.txs_with_heights()}
    mp2.close()
    recovery_exact = recovered == survivors

    # -- phase 4: commit everything, then resubmit evicted txs — their
    # hashes must have left the dedup cache (admitted now), while a
    # COMMITTED tx must stay permanently deduped -----------------------
    committed = mp.reap(-1)
    mp.update(1, committed)
    resample = ctx.rng("resubmit").sample(
        evicted_txs, min(len(evicted_txs), 12))
    resubmit_outcomes = [submit(tx) for tx in resample]
    committed_resubmit = (submit(committed[0]) if committed
                          else "admitted")
    b1 = _admit_buckets()
    rejected_d = _rejected_total() - rejected0
    evicted_d = _evicted_total() - evicted0
    mp.close()
    offered = sum(outcomes.values())
    admitted = outcomes["admitted"]
    unaccounted = (offered - admitted) - rejected_d
    budget_metrics = {
        "priority_inversions": inversions[0],
        "unaccounted_rejections": unaccounted,
        "evictions": evicted_d,
        "admit_p99_s": round(_delta_quantile(b0, b1, 0.99), 6),
    }
    ctx.note("storm.result", offered=offered, outcomes=dict(outcomes),
             survivors=len(survivors), recovered=recovered_n,
             resubmitted=len(resample), **budget_metrics)
    return {"offered": offered, "outcomes": outcomes,
            "filled": filled, "evict_log_len": len(evict_log),
            "rejected_delta": rejected_d, "evicted_delta": evicted_d,
            "recovery_exact": recovery_exact,
            "recovered_count": recovered_n,
            "survivor_count": len(survivors),
            "resubmit_outcomes": resubmit_outcomes,
            "committed_resubmit": committed_resubmit,
            "budget_metrics": budget_metrics}


def _storm_safety_no_inversion(ctx, obs):
    inv.require(obs["budget_metrics"]["priority_inversions"] == 0,
                f"{obs['budget_metrics']['priority_inversions']} "
                f"higher-priority txs were evicted while a "
                f"lower-priority tx survived")


def _storm_safety_accounting(ctx, obs):
    out = obs["outcomes"]
    inv.require(out["error"] == 0,
                f"{out['error']} submissions raised instead of "
                f"returning a typed outcome")
    inv.require(obs["budget_metrics"]["unaccounted_rejections"] == 0,
                f"{obs['budget_metrics']['unaccounted_rejections']} "
                f"rejections missing from mempool_rejected{{reason}} "
                f"— silent drops")
    inv.require(obs["evicted_delta"] == obs["evict_log_len"],
                "mempool_evicted disagrees with the eviction hook — "
                "an eviction went uncounted")


def _storm_safety_resubmission(ctx, obs):
    inv.require(obs["resubmit_outcomes"] and
                all(k != "dup" for k in obs["resubmit_outcomes"]),
                f"an evicted tx was still dedup-cached on resubmit: "
                f"{obs['resubmit_outcomes']}")
    inv.require(all(k == "admitted" for k in obs["resubmit_outcomes"]),
                f"evicted txs failed to re-enter an emptied pool: "
                f"{obs['resubmit_outcomes']}")
    inv.require(obs["committed_resubmit"] == "dup",
                f"a COMMITTED tx re-entered as "
                f"'{obs['committed_resubmit']}' — committed txs must "
                f"stay permanently deduped")


def _storm_safety_recovery(ctx, obs):
    inv.require(obs["recovery_exact"],
                f"recover_wal resurrected a set of "
                f"{obs['recovered_count']} txs != the "
                f"{obs['survivor_count']} storm survivors — an "
                f"evicted tx came back (or a survivor was lost)")


def _storm_liveness(ctx, obs):
    inv.require(obs["filled"] == STORM_POOL,
                f"pool never reached its cap ({obs['filled']}/"
                f"{STORM_POOL}) — the storm tested nothing")
    inv.require(obs["evicted_delta"] >= 10,
                f"only {obs['evicted_delta']} evictions — the storm "
                f"never stormed")
    inv.require(obs["outcomes"]["full"] >= 10,
                f"only {obs['outcomes']['full']} full rejections — "
                f"low-priority shedding went unexercised")


register(
    "eviction-storm",
    "a capped pool under a mixed-priority storm: evictions are "
    "lowest-priority-oldest with zero priority inversions, every "
    "submission lands in exactly one counted outcome (zero silent "
    "drops), evicted hashes leave the dedup cache so resubmission "
    "works, committed txs stay deduped, and a crash + recover_wal "
    "resurrects exactly the surviving set",
    safety=[("no-priority-inversion", _storm_safety_no_inversion),
            ("zero-silent-drops", _storm_safety_accounting),
            ("evicted-resubmits-committed-does-not",
             _storm_safety_resubmission),
            ("wal-recovers-survivors-only", _storm_safety_recovery)],
    liveness=[("storm-reached-overload", _storm_liveness)],
    smoke=True, budget_s=180.0,
    budgets={"priority_inversions": {"max": 0},
             "unaccounted_rejections": {"max": 0},
             "evictions": {"min": 10},
             "admit_p99_s": {"max": 0.5}})(_storm_body)
