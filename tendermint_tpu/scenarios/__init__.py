"""Deterministic Byzantine + failure scenario harness.

`run_scenario(name, seed)` runs one registered scenario: a declarative
composition of fault injectors (byzantine vote streams, evidence
floods, stale/partial-commit replay, partitions, crash-restart storms,
device-fault storms) with a post-mortem of safety and liveness
invariants and flight-recorder artifacts on failure.  See `engine.py`
for the seed-replay contract and `catalog.py` for the shipped
scenarios; drive from the command line with `cli chaos`.
"""

from tendermint_tpu.scenarios.engine import (CHAOS_RUN_SCHEMA,
                                             DEFAULT_CHAOS_LEDGER,
                                             DEFAULT_SEED, KNOWN_BACKENDS,
                                             SCENARIOS,
                                             InvariantViolation,
                                             ScenarioResult, artifacts_root,
                                             parse_seed_range, register,
                                             resolve_backend,
                                             run_scenario, run_sweep)
from tendermint_tpu.scenarios import catalog  # registers the shipped set
from tendermint_tpu.scenarios import live    # registers the big-rig tier
from tendermint_tpu.scenarios import statesync_scenarios  # snapshot tier
from tendermint_tpu.scenarios import batchplane_scenarios  # verify plane
from tendermint_tpu.scenarios import mempool_scenarios  # ingress overload
from tendermint_tpu.scenarios.catalog import SMOKE_ORDER

__all__ = ["CHAOS_RUN_SCHEMA", "DEFAULT_CHAOS_LEDGER", "DEFAULT_SEED",
           "KNOWN_BACKENDS", "SCENARIOS", "SMOKE_ORDER",
           "InvariantViolation", "ScenarioResult", "artifacts_root",
           "batchplane_scenarios", "catalog", "live",
           "mempool_scenarios", "parse_seed_range", "register",
           "resolve_backend",
           "run_scenario", "run_sweep", "statesync_scenarios"]
