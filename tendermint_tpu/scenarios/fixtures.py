"""Deterministic chain fixtures: N priv-validators producing a valid
chain of blocks with real commits.

This is the analog of the reference's validatorStub fixtures
(`consensus/common_test.go:48-106`), promoted out of the test tree so
the scenario engine (and `cli chaos`) can build chains standalone;
`tests/chainutil.py` re-exports everything here for the test suite.
"""

from __future__ import annotations

from tendermint_tpu.types import (Block, BlockID, Commit, EMPTY_COMMIT,
                                  GenesisDoc, GenesisValidator, PrivKey,
                                  PrivValidator, TYPE_PRECOMMIT, Validator,
                                  ValidatorSet, Vote, VoteSet, ZERO_BLOCK_ID)
from tendermint_tpu.types.part_set import PART_SIZE as _PROD_PART_SIZE

__all__ = ["PART_SIZE", "make_validators", "make_genesis", "sign_vote",
           "make_commit", "kvstore_app_hashes", "build_chain"]

# the production part size: fast-sync re-chunks blocks with the default,
# so fixture commits must sign the same parts header it will recompute
PART_SIZE = _PROD_PART_SIZE


def make_validators(n: int, power: int = 10, seed: int = 0):
    """Deterministic keys so fixtures are reproducible."""
    privs = [PrivValidator(PrivKey(bytes([seed + 1, i + 1]) + b"\x00" * 30))
             for i in range(n)]
    vs = ValidatorSet([Validator(p.pub_key, power) for p in privs])
    privs.sort(key=lambda p: p.address)
    return privs, vs


def make_genesis(chain_id: str, privs, power: int = 10) -> GenesisDoc:
    return GenesisDoc(
        chain_id=chain_id,
        validators=[GenesisValidator(p.pub_key.bytes_, power)
                    for p in privs],
        genesis_time_ns=1_000_000_000)


def sign_vote(priv: PrivValidator, vs: ValidatorSet, chain_id: str,
              height: int, round_: int, type_: int, block_id) -> Vote:
    idx = vs.index_of(priv.address)
    v = Vote(validator_address=priv.address, validator_index=idx,
             height=height, round=round_, type=type_, block_id=block_id)
    return Vote(**{**v.__dict__,
                   "signature": priv.sign_vote(chain_id, v)})


def make_commit(privs, vs: ValidatorSet, chain_id: str, height: int,
                block_id, round_: int = 0) -> Commit:
    # sign across validators in parallel (independent keys, native signing
    # releases the GIL) — big bench chains need hundreds of thousands of
    # votes; accounting stays sequential
    votes = list(_sign_pool().map(
        lambda p: sign_vote(p, vs, chain_id, height, round_,
                            TYPE_PRECOMMIT, block_id), privs))
    vset = VoteSet(chain_id, height, round_, TYPE_PRECOMMIT, vs)
    for v in votes:
        vset.add_vote(v)
    return vset.make_commit()


_pool = None


def _sign_pool():
    global _pool
    if _pool is None:
        from concurrent.futures import ThreadPoolExecutor
        _pool = ThreadPoolExecutor(8)
    return _pool


def kvstore_app_hashes(n: int, txs_per_block: int = 2) -> list[bytes]:
    """App hashes for a kvstore app fed build_chain's deterministic txs:
    entry i is the hash going INTO block i+1."""
    from tendermint_tpu.abci.app import create_app
    app = create_app("kvstore")
    hashes = [b""]
    for h in range(1, n + 1):
        for i in range(txs_per_block):
            app.deliver_tx(b"tx-%d-%d" % (h, i))
        hashes.append(app.commit().data)
    return hashes[:-1]


def build_chain(privs, vs: ValidatorSet, chain_id: str, n_blocks: int,
                txs_per_block: int = 2, app_hashes: list[bytes] | None = None,
                part_size: int = PART_SIZE):
    """Returns [(block, part_set, seen_commit)] for heights 1..n.

    app_hashes[i] is the app hash *going into* block i+1 (i.e. after block
    i executed); defaults to empty (nilapp semantics).
    """
    out = []
    last_commit = EMPTY_COMMIT
    last_block_id = ZERO_BLOCK_ID
    vals_hash = vs.hash()
    for h in range(1, n_blocks + 1):
        app_hash = (app_hashes[h - 1] if app_hashes else b"")
        txs = [b"tx-%d-%d" % (h, i) for i in range(txs_per_block)]
        block = Block.make(chain_id=chain_id, height=h,
                           time_ns=1_000_000_000 + h, txs=txs,
                           last_commit=last_commit,
                           last_block_id=last_block_id,
                           validators_hash=vals_hash, app_hash=app_hash)
        ps = block.make_part_set(part_size)
        block_id = BlockID(block.hash(), ps.header)
        seen = make_commit(privs, vs, chain_id, h, block_id)
        out.append((block, ps, seen))
        last_commit = seen
        last_block_id = block_id
    return out
