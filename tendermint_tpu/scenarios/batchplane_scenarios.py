"""Batch-plane isolation scenarios: concurrent workloads sharing the
one verify scheduler must coalesce, not collide.

ROADMAP item 2's acceptance bar: replay (fast-sync commit verifies)
and a light-client query stream running concurrently through
`batchplane/scheduler.py` each keep >=70% of the throughput they get
with the device to themselves, and the batch-occupancy evidence shows
WHY — their lanes ride the same flushed chunks, so sharing the chip
costs amortized padding instead of serialized half-full batches.  This
is the Blockchain Machine claim (arXiv:2104.06968) made falsifiable:
one batch crypto pipeline multiplexing all protocol traffic beats one
pipeline per producer.

The producers are PACED (submit, wait, think), not device-saturating
closed loops, because the retention bar is about scheduling, not raw
capacity.  On the CPU backend a verify flush costs ~linearly per lane
(measured on the tier-1 rig: bucket 16 ~0.21s, 32 ~0.39s, 64 ~0.76s
warm), so two producers saturating one core can each keep at most
~f16/f32 = 55% no matter how the scheduler slices — while on a TPU
the same doubling is overhead-dominated and nearly free.  Paced below
saturation, the deadline window phase-locks the two producers into
shared flushes (both unblock on the same flush, think the same time,
resubmit inside the same 20 ms deadline), which is exactly the mixed-
batch amortization the plane exists to provide.

The lane counts are COMPLEMENTARY on purpose: replay submits 11
lanes, light 5, so alone each pads a half-full power-of-2 chunk
(11/16, 5/8) while merged they fill bucket 16 exactly — the shared
flush rides the SAME pre-warmed executable replay uses alone, which
is why the concurrent occupancy mean must beat the single-producer
baseline and why coalescing is nearly free.

Two tiers, one body:

- `batchplane-isolation` (smoke, tier-1): CPU-scaled — 11+5 lane
  calls on chunk shapes the suite already compiles, ~25 s of wall
  clock.
- `batchplane-flood-isolation` (stress, faults+slow): 8x the lanes per
  call with the retention bar declared as a metric budget, so every
  nightly seed lands a retention number in `CHAOS_LEDGER.jsonl` and a
  slow isolation regression trips the chaos gate rather than hiding
  behind a green invariant.

Both producers submit grouped verifies against the SAME validator set
(one comb table, one merge key) — the configuration the plane exists
for; disjoint sets cannot share a chunk and degrade to time-slicing.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from tendermint_tpu import batchplane
from tendermint_tpu.crypto import pure_ed25519 as ref
from tendermint_tpu.scenarios import invariants as inv
from tendermint_tpu.scenarios.engine import register

MSG_LEN = 96          # the vote sign-bytes length every warm shape uses
V = 4                 # validator-set size (one comb table build)


def _signed_lanes(rng, lanes: int):
    """`lanes` real ed25519 lanes over a V-validator set, signed once
    from seed-derived keys; drives resubmit the same arrays (the device
    cannot tell a repeated signature from a fresh one)."""
    seeds = [rng.randbytes(32) for _ in range(V)]
    pubs = [ref.pubkey_from_seed(s) for s in seeds]
    vp = np.frombuffer(b"".join(pubs), np.uint8).reshape(V, 32)
    idx = (np.arange(lanes) % V).astype(np.int64)
    msgs = [rng.randbytes(MSG_LEN) for _ in range(lanes)]
    sigs = [ref.sign(seeds[idx[i]], msgs[i]) for i in range(lanes)]
    ma = np.frombuffer(b"".join(msgs), np.uint8).reshape(lanes, MSG_LEN)
    sa = np.frombuffer(b"".join(sigs), np.uint8).reshape(lanes, 64)
    return vp, idx, ma, sa


class _Producer:
    """Paced driver: N rounds of submit -> wait -> think.  Throughput
    is lanes over the time from first submission to last result; with
    a fixed round count the retention ratio reduces to iso_elapsed /
    conc_elapsed, immune to end-of-phase quantization."""

    def __init__(self, name, klass, set_key, vp, idx, msgs, sigs,
                 rounds: int, think_s: float,
                 barrier: threading.Barrier | None = None):
        self.name, self.klass = name, klass
        self.args = (set_key, vp, idx, msgs, sigs)
        self.rounds = rounds
        self.think_s = think_s
        self.barrier = barrier
        self.lanes_per_call = len(idx)
        self.elapsed = 0.0
        self.bad_lanes = 0
        self.error: BaseException | None = None

    def run(self) -> None:
        plane = batchplane.get_plane()
        try:
            if self.barrier is not None:
                self.barrier.wait(timeout=30.0)
            t0 = time.perf_counter()
            for i in range(self.rounds):
                ok = plane.submit_grouped(
                    *self.args, producer=self.name,
                    klass=self.klass).wait()
                self.bad_lanes += int((~ok).sum())
                self.elapsed = time.perf_counter() - t0
                if i + 1 < self.rounds:
                    time.sleep(self.think_s)
        except BaseException as e:          # surfaced as an invariant
            self.error = e

    @property
    def lanes_per_sec(self) -> float:
        return (self.rounds * self.lanes_per_call
                / max(self.elapsed, 1e-9))


def _plane_deltas(ctx, start: str, end: str) -> dict:
    """Batch-plane counter movement between two metric snapshots."""
    a = ctx.metrics(start) or {}
    b = ctx.metrics(end) or {}

    def d(key):
        return (b.get(key) or 0) - (a.get(key) or 0)

    occ_a = a.get("batchplane_occupancy") or {}
    occ_b = b.get("batchplane_occupancy") or {}
    n = (occ_b.get("count", 0) or 0) - (occ_a.get("count", 0) or 0)
    s = (occ_b.get("sum", 0.0) or 0.0) - (occ_a.get("sum", 0.0) or 0.0)
    return {"flushes": d("batchplane_flushes"),
            "mixed": d("batchplane_mixed_batches"),
            "occupancy_mean": (s / n) if n else 0.0}


def _run_pair(producers: list) -> None:
    ths = [threading.Thread(target=p.run, daemon=True)
           for p in producers]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    for p in producers:
        if p.error is not None:
            raise p.error


def _isolation(ctx, fastsync_lanes: int, light_lanes: int,
               rounds: int, think_s: float):
    rng = ctx.rng("lanes")
    set_key = b"batchplane-isolation"
    total = fastsync_lanes + light_lanes
    vp, idx, ma, sa = _signed_lanes(rng, total)
    per_producer = {
        "fastsync": (idx[:fastsync_lanes], ma[:fastsync_lanes],
                     sa[:fastsync_lanes]),
        "light": (idx[fastsync_lanes:], ma[fastsync_lanes:],
                  sa[fastsync_lanes:]),
    }
    ctx.plan("isolation.rig", fastsync_lanes=fastsync_lanes,
             light_lanes=light_lanes, rounds=rounds, think_s=think_s,
             validators=V)

    def producer(name, klass, barrier=None, rounds_=None):
        pidx, pma, psa = per_producer[name]
        return _Producer(name, klass, set_key, vp, pidx, pma, psa,
                         rounds_ or rounds, think_s, barrier=barrier)

    batchplane.reset_plane()
    try:
        # warm the table build + both chunk shapes OUTSIDE the timed
        # phases: one solo round (iso bucket) and one barrier-aligned
        # pair (the doubled concurrent bucket) — on a cold XLA cache
        # this is where the compiles land
        for name, klass in (("fastsync", batchplane.CLASS_FASTSYNC),
                            ("light", batchplane.CLASS_LIGHT)):
            w = producer(name, klass, rounds_=1)
            w.run()
            if w.error is not None:
                raise w.error
        bar = threading.Barrier(2)
        _run_pair([producer("fastsync", batchplane.CLASS_FASTSYNC,
                            barrier=bar, rounds_=1),
                   producer("light", batchplane.CLASS_LIGHT,
                            barrier=bar, rounds_=1)])

        # -- isolated baselines: each producer alone ------------------
        ctx.snapshot_metrics("iso-start")
        iso = {}
        for name, klass in (("fastsync", batchplane.CLASS_FASTSYNC),
                            ("light", batchplane.CLASS_LIGHT)):
            p = producer(name, klass)
            p.run()
            if p.error is not None:
                raise p.error
            iso[name] = p
        batchplane.get_plane().drain()
        ctx.snapshot_metrics("conc-start")

        # -- concurrent: barrier-started so round 1 already coalesces;
        # after that the shared flush keeps them phase-locked ----------
        bar = threading.Barrier(2)
        conc = {"fastsync": producer("fastsync",
                                     batchplane.CLASS_FASTSYNC,
                                     barrier=bar),
                "light": producer("light", batchplane.CLASS_LIGHT,
                                  barrier=bar)}
        _run_pair(list(conc.values()))
        batchplane.get_plane().drain()
        ctx.snapshot_metrics("end")
    finally:
        batchplane.reset_plane()

    iso_d = _plane_deltas(ctx, "iso-start", "conc-start")
    conc_d = _plane_deltas(ctx, "conc-start", "end")
    retention = {n: (conc[n].lanes_per_sec / iso[n].lanes_per_sec
                     if iso[n].lanes_per_sec > 0 else 0.0)
                 for n in iso}
    ctx.note("isolation.result",
             iso_lps={n: round(p.lanes_per_sec, 1)
                      for n, p in iso.items()},
             conc_lps={n: round(p.lanes_per_sec, 1)
                       for n, p in conc.items()},
             retention={n: round(r, 3) for n, r in retention.items()},
             iso_occupancy=round(iso_d["occupancy_mean"], 3),
             conc_occupancy=round(conc_d["occupancy_mean"], 3),
             mixed_flushes=conc_d["mixed"], flushes=conc_d["flushes"])
    return {"iso_elapsed": {n: round(p.elapsed, 3)
                            for n, p in iso.items()},
            "conc_elapsed": {n: round(p.elapsed, 3)
                             for n, p in conc.items()},
            "bad_lanes": sum(p.bad_lanes for p in
                             list(iso.values()) + list(conc.values())),
            "retention_fastsync": retention["fastsync"],
            "retention_light": retention["light"],
            "iso_occupancy_mean": iso_d["occupancy_mean"],
            "conc_occupancy_mean": conc_d["occupancy_mean"],
            "conc_flushes": conc_d["flushes"],
            "conc_mixed_flushes": conc_d["mixed"],
            "budget_metrics": {
                "retention_fastsync": round(retention["fastsync"], 3),
                "retention_light": round(retention["light"], 3),
                "conc_occupancy_mean":
                    round(conc_d["occupancy_mean"], 3),
                "mixed_flush_frac": round(
                    conc_d["mixed"] / max(conc_d["flushes"], 1), 3)}}


def _safety_retention(ctx, obs):
    inv.require(obs["retention_fastsync"] >= 0.7,
                f"replay kept only "
                f"{obs['retention_fastsync']:.0%} of its isolated "
                f"throughput under a concurrent light stream "
                f"(bar: 70%)")
    inv.require(obs["retention_light"] >= 0.7,
                f"light stream kept only "
                f"{obs['retention_light']:.0%} of its isolated "
                f"throughput while replay ran (bar: 70%)")


def _safety_coalescing(ctx, obs):
    # the MECHANISM behind the retention: concurrent lanes share
    # flushed chunks instead of padding separate half-full batches
    inv.require(obs["conc_mixed_flushes"] >= 1,
                "no flush carried lanes from both producers — the "
                "plane time-sliced instead of coalescing")
    inv.require(obs["conc_occupancy_mean"]
                > obs["iso_occupancy_mean"],
                f"concurrent occupancy "
                f"{obs['conc_occupancy_mean']:.2f} did not beat the "
                f"single-producer baseline "
                f"{obs['iso_occupancy_mean']:.2f}")


def _safety_correctness(ctx, obs):
    inv.require(obs["bad_lanes"] == 0,
                f"{obs['bad_lanes']} valid signatures verified False "
                f"under the shared plane")


def _liveness_both_finish(ctx, obs):
    for n in ("fastsync", "light"):
        inv.require(obs["conc_elapsed"][n] > 0,
                    f"{n} never completed its rounds under "
                    f"contention — starved")


_SAFETY = [("retention-70pct", _safety_retention),
           ("mixed-batches-prove-coalescing", _safety_coalescing),
           ("no-wrong-answers", _safety_correctness)]
_LIVENESS = [("both-producers-finish", _liveness_both_finish)]


def _isolation_smoke(ctx):
    # CPU-scaled: 11+5 lanes (buckets 16 and 8 alone, exactly 16
    # merged — the suite's warmest grouped shape), ~25s measured
    return _isolation(ctx, fastsync_lanes=11, light_lanes=5,
                      rounds=6, think_s=1.0)


def _isolation_flood(ctx):
    # 8x the lanes per call (88+40 -> bucket 128 merged); think time
    # scaled so the paced load still fits the CPU rig's capacity (see
    # module docstring)
    return _isolation(ctx, fastsync_lanes=88, light_lanes=40,
                      rounds=6, think_s=4.0)


register(
    "batchplane-isolation",
    "replay and a light-client stream share the unified batch plane: "
    "run each alone, then both concurrently — each must keep >=70% of "
    "its isolated lanes/sec, with mixed-producer flushes and a "
    "concurrent occupancy mean above the single-producer baseline "
    "proving the lanes coalesced (11+5 complementary lanes fill "
    "bucket 16 exactly) instead of time-slicing (CPU-scaled tier-1 "
    "twin of batchplane-flood-isolation)",
    safety=_SAFETY, liveness=_LIVENESS,
    smoke=True, budget_s=240.0)(_isolation_smoke)


register(
    "batchplane-flood-isolation",
    "the batchplane-isolation rig at flood scale (88+40 lane calls): "
    "per-producer throughput retention >=70% and the coalescing "
    "evidence are declared metric budgets, so every nightly seed "
    "ledgers a retention number and a slow isolation regression trips "
    "the chaos gate",
    safety=_SAFETY, liveness=_LIVENESS,
    smoke=False, budget_s=600.0,
    budgets={"retention_fastsync": {"min": 0.7},
             "retention_light": {"min": 0.7},
             "conc_occupancy_mean": {"min": 0.05},
             "mixed_flush_frac": {"min": 0.5}})(_isolation_flood)
