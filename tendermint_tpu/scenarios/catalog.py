"""The shipped scenario catalogue.

Each scenario composes injectors from `injectors.py` over a rig from
`harness.py`, returns an observations dict, and registers at least one
safety and one liveness invariant.  Smoke scenarios (`smoke=True`) are
the fast subset tier-1 runs on every push; the rest are the
`faults`-marked stress tier (`tests/test_scenarios_slow.py`).

Adversary models for the fast-sync scenarios follow the deterministic-
finality literature: stale finality proofs (PoTE, arXiv:2512.09409) and
partial-commit replay (ACE, arXiv:2603.10242) — a byzantine block
server re-presenting yesterday's commit, or a quorum certificate pruned
below +2/3, for blocks it wants a syncing node to accept.
"""

from __future__ import annotations

import contextlib
import os
import tempfile
import threading
import time

import numpy as np

from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.crypto import backend as cb
from tendermint_tpu.crypto.backend import PythonBackend
from tendermint_tpu.crypto.supervised import CLOSED, SupervisedBackend
from tendermint_tpu.p2p.switch import connect_switches
from tendermint_tpu.scenarios import fixtures, harness, injectors
from tendermint_tpu.scenarios import invariants as inv
from tendermint_tpu.scenarios.engine import register
from tendermint_tpu.state.evidence import EvidencePool
from tendermint_tpu.utils import chaos as chaosmod
from tendermint_tpu.utils.db import MemDB
from tendermint_tpu.utils.metrics import REGISTRY


@contextlib.contextmanager
def _python_backend():
    old = cb._current
    cb.set_backend("python")
    try:
        yield
    finally:
        cb._current = old


# ===========================================================================
# byz-equivocation (smoke)
# ===========================================================================

def _byz_equivocation(ctx):
    chain_id = "chaos-equivocation"
    target = 4
    with _python_backend():
        nodes, _privs, _gen = harness.wire_net(chain_id, 4, seed=1)
        byz = nodes[0]
        heights = injectors.plan_heights(ctx, "equivocation",
                                         1, target + 2, k=3)
        evidence: list = []
        ev_lock = threading.Lock()
        for nd in nodes[1:]:
            nd.cs.evsw.subscribe(
                "scenario", "EvidenceDoubleSign",
                lambda e: (ev_lock.acquire(), evidence.append(e),
                           ev_lock.release()))
        injectors.equivocate(ctx, byz, byz.priv, chain_id, heights)
        for nd in nodes:
            nd.cs.start()
        try:
            nodes[1].mempool.check_tx(b"chaos=equivocation")
            reached = harness.wait_until(
                lambda: all(nd.block_store.height >= target
                            for nd in nodes[1:]), timeout=60)
            captured = harness.wait_until(lambda: bool(evidence),
                                          timeout=20)
        finally:
            for nd in nodes:
                nd.cs.stop()
    with ev_lock:
        ev_count = len(evidence)
        ev_ok = all(
            e.vote_a.validator_address == byz.priv.address
            and e.vote_a.block_id.key() != e.vote_b.block_id.key()
            for e in evidence)
    ctx.note("equivocation.result", evidence=ev_count,
             heights=[nd.block_store.height for nd in nodes])
    return {"reached": reached, "captured": captured,
            "evidence_count": ev_count, "evidence_wellformed": ev_ok,
            "honest_heights": [nd.block_store.height for nd in nodes[1:]],
            "_honest_stores": [nd.block_store for nd in nodes[1:]]}


def _equiv_safety_agreement(ctx, obs):
    inv.no_conflicting_commits(obs["_honest_stores"])


def _equiv_safety_evidence(ctx, obs):
    inv.require(obs["captured"] and obs["evidence_count"] >= 1,
                "honest nodes captured no DuplicateVoteEvidence — the "
                "double votes were accepted silently")
    inv.require(obs["evidence_wellformed"],
                "captured evidence does not accuse the byzantine "
                "validator with conflicting block ids")


def _equiv_liveness(ctx, obs):
    inv.completed(obs, "reached",
                  "honest nodes' height progress under equivocation")


register(
    "byz-equivocation",
    "1 of 4 validators double-signs prevotes at seed-chosen heights; "
    "honest nodes must keep committing identical blocks and capture "
    "DuplicateVoteEvidence",
    safety=[("no-conflicting-commits", _equiv_safety_agreement),
            ("equivocation-evidenced", _equiv_safety_evidence)],
    liveness=[("honest-progress", _equiv_liveness)],
    smoke=True)(_byz_equivocation)


# ===========================================================================
# evidence-flood (smoke)
# ===========================================================================

def _evidence_flood(ctx):
    chain_id = "chaos-evflood"
    with _python_backend():
        privs, vs = fixtures.make_validators(4, seed=2)
        pool = EvidencePool(MemDB(), chain_id)
        real, bogus = injectors.fabricate_evidence(
            ctx, privs, vs, chain_id, n_real=6, n_bogus=18)
        # a solo validator keeps committing while the flood lands
        nodes, _, _ = harness.wire_net(chain_id, 1, seed=3)
        solo = nodes[0]
        solo.cs.start()
        try:
            h_before = solo.block_store.height
            salvo = ([("real", e) for e in real]
                     + [("bogus", e) for e in bogus])
            ctx.rng("flood-order").shuffle(salvo)
            accepted = {"real": 0, "bogus": 0}
            for kind, e in salvo:
                if pool.add(e, vs):
                    accepted[kind] += 1
            flood_done_h = solo.block_store.height
            progressed = harness.wait_until(
                lambda: solo.block_store.height >= flood_done_h + 2,
                timeout=30)
            h_after = solo.block_store.height
        finally:
            solo.cs.stop()
    ctx.note("flood.result", accepted=accepted, pool_size=pool.size())
    return {"accepted_real": accepted["real"],
            "accepted_bogus": accepted["bogus"],
            "pool_size": pool.size(), "n_real": len(real),
            "n_bogus": len(bogus), "progressed": progressed,
            "h_before": h_before, "h_after": h_after}


def _flood_safety(ctx, obs):
    inv.require(obs["accepted_bogus"] == 0,
                f"pool accepted {obs['accepted_bogus']} fabricated "
                f"evidence items — forged proofs were silently believed")
    inv.require(obs["accepted_real"] == obs["n_real"]
                and obs["pool_size"] == obs["n_real"],
                f"pool holds {obs['pool_size']} items, expected exactly "
                f"the {obs['n_real']} real proofs "
                f"(accepted_real={obs['accepted_real']})")


def _flood_liveness(ctx, obs):
    inv.completed(obs, "progressed",
                  "solo validator progress during/after evidence flood")
    inv.height_progressed("solo validator", obs["h_before"],
                          obs["h_after"], min_delta=2)


register(
    "evidence-flood",
    "a pool is flooded with fabricated equivocation proofs (strangers, "
    "agreeing votes, torn signatures) mixed with real ones; only the "
    "real ones may land, and consensus keeps committing",
    safety=[("only-valid-evidence", _flood_safety)],
    liveness=[("commit-progress", _flood_liveness)],
    smoke=True)(_evidence_flood)


# ===========================================================================
# device-rung-walk (smoke)
# ===========================================================================

N_RUNGWALK_BLOCKS = 48


def _device_rung_walk(ctx):
    chain_id = "chaos-rungwalk"
    spec = "raise:every=18"
    ctx.plan("crypto-chaos", spec=spec)
    # the programmatic TM_CHAOS_CRYPTO path: install the validated config
    # and let the supervisor pick it up via CryptoChaos.current()
    chaosmod.install(chaosmod.ChaosConfig(seed=ctx.seed, crypto=spec))
    with _python_backend():
        privs, vs = fixtures.make_validators(4, seed=4)
        gen = fixtures.make_genesis(chain_id, privs)
        hashes = fixtures.kvstore_app_hashes(N_RUNGWALK_BLOCKS)
        chain = fixtures.build_chain(privs, vs, chain_id,
                                     N_RUNGWALK_BLOCKS, app_hashes=hashes)
        src_sw, _, src_store = harness.fastsync_source(chain_id, chain, gen)
        sync_sw, bc, _cons, sync_store = harness.fastsync_syncer(
            chain_id, gen, batch_size=2)
        sup = SupervisedBackend(
            [("dev", PythonBackend()), ("python", PythonBackend())],
            breaker_threshold=1, breaker_cooldown_s=0.2,
            retries=0, call_timeout_s=30.0)
        evicted: list = []
        orig_evict = bc.pool.on_evict
        bc.pool.on_evict = lambda p, r: (evicted.append(p),
                                         orig_evict and orig_evict(p, r))
        trips0 = REGISTRY.crypto_breaker_trips.value
        recov0 = REGISTRY.crypto_breaker_recoveries.value
        old = cb._current
        cb._current = sup
        src_sw.start(); sync_sw.start()
        try:
            connect_switches(sync_sw, src_sw)
            deadline = time.time() + 90
            snapped = False
            while (sync_store.height < N_RUNGWALK_BLOCKS - 1
                   and time.time() < deadline):
                if (REGISTRY.crypto_breaker_trips.value > trips0
                        and sup.chaos is not None and sup.chaos.active):
                    # fault storm "clears" after the first trip; from
                    # here the half-open probe must restore the rung
                    ctx.snapshot_metrics("faulted")
                    snapped = True
                    sup.chaos.active = False
                    ctx.note("chaos.cleared", mode=sup.chaos.mode)
                time.sleep(0.02)
            if not snapped:
                ctx.snapshot_metrics("faulted")
            synced = sync_store.height >= N_RUNGWALK_BLOCKS - 1
            # drive half-open probes until the breaker recovers
            from tendermint_tpu.crypto import pure_ed25519 as ref
            seed32 = bytes(32)
            pub = np.frombuffer(ref.pubkey_from_seed(seed32), np.uint8)
            msg = np.zeros(32, np.uint8)
            sig = np.frombuffer(ref.sign(seed32, msg.tobytes()), np.uint8)
            deadline = time.time() + 10
            while (REGISTRY.crypto_breaker_recoveries.value == recov0
                   and time.time() < deadline):
                sup.verify_batch(pub[None, :], msg[None, :], sig[None, :])
                time.sleep(0.05)
            recovered = (REGISTRY.crypto_breaker_recoveries.value > recov0
                         and sup._rungs[0].state == CLOSED)
            chain_ok = all(
                sync_store.load_block(h).hash()
                == src_store.load_block(h).hash()
                for h in range(1, min(sync_store.height,
                                      N_RUNGWALK_BLOCKS - 2) + 1))
            app_hash_ok = bc.state.app_hash == hashes[-1]
        finally:
            src_sw.stop(); sync_sw.stop()
            cb._current = old
    status = sup.supervisor_status()
    ctx.note("rungwalk.result", synced_height=sync_store.height,
             recovered=recovered, active_rung=status.get("active_rung"),
             evicted=evicted)
    return {"synced": synced, "recovered": recovered,
            "chain_ok": chain_ok, "app_hash_ok": app_hash_ok,
            "evicted": evicted, "synced_height": sync_store.height}


def _rungwalk_safety(ctx, obs):
    inv.no_silent_acceptance(ctx)
    inv.require(obs["chain_ok"] and obs["app_hash_ok"],
                "synced state diverged from the source under device "
                f"faults (chain_ok={obs['chain_ok']}, "
                f"app_hash_ok={obs['app_hash_ok']})")


def _rungwalk_safety_no_blame(ctx, obs):
    inv.require(not obs["evicted"],
                f"peers evicted for OUR injected device faults: "
                f"{obs['evicted']}")


def _rungwalk_liveness(ctx, obs):
    inv.completed(obs, "synced", "fast-sync under device-fault storm")
    inv.metric_increased(ctx, "blocks_synced")


def _rungwalk_liveness_recovery(ctx, obs):
    inv.metric_increased(ctx, "crypto_breaker_trips")
    inv.require(obs["recovered"],
                "device rung never recovered (breaker stayed open) "
                "after the fault storm cleared")


register(
    "device-rung-walk",
    "sustained device faults during fast-sync force supervised-ladder "
    "demotion; the breaker trips, the sync completes on fallback rungs "
    "with byte-identical state, and the rung recovers once faults clear",
    safety=[("no-silent-acceptance", _rungwalk_safety),
            ("no-peer-blame", _rungwalk_safety_no_blame)],
    liveness=[("sync-completes", _rungwalk_liveness),
              ("rung-recovers", _rungwalk_liveness_recovery)],
    smoke=True)(_device_rung_walk)


# ===========================================================================
# device-wrong-answer (smoke)
# ===========================================================================

def _device_wrong_answer(ctx):
    spec = "wrong:lanes=1,every=3"
    ctx.plan("crypto-chaos", spec=spec)
    chaosmod.install(chaosmod.ChaosConfig(seed=ctx.seed, crypto=spec))
    sup = SupervisedBackend(
        [("dev", PythonBackend()), ("python", PythonBackend())],
        breaker_threshold=3, breaker_cooldown_s=0.1,
        retries=0, call_timeout_s=30.0, spot_check_every=1)
    from tendermint_tpu.crypto import pure_ed25519 as ref
    rng = ctx.rng("vectors")
    n_calls = 12
    ctx.plan("verify-calls", n=n_calls)
    wrong = 0
    for i in range(n_calls):
        seed32 = bytes(rng.randrange(256) for _ in range(32))
        msg = bytes(rng.randrange(256) for _ in range(32))
        good = rng.randrange(2) == 0
        sig = ref.sign(seed32, msg)
        if not good:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        pub = np.frombuffer(ref.pubkey_from_seed(seed32), np.uint8)
        out = sup.verify_batch(pub[None, :],
                               np.frombuffer(msg, np.uint8)[None, :],
                               np.frombuffer(sig, np.uint8)[None, :])
        if bool(out[0]) != good:
            wrong += 1
    ctx.snapshot_metrics("faulted")
    if sup.chaos is not None:
        sup.chaos.active = False
    # after the storm clears the device rung must serve clean answers
    seed32 = bytes(32)
    msg = bytes(32)
    sig = ref.sign(seed32, msg)
    pub = np.frombuffer(ref.pubkey_from_seed(seed32), np.uint8)
    out = sup.verify_batch(pub[None, :],
                           np.frombuffer(msg, np.uint8)[None, :],
                           np.frombuffer(sig, np.uint8)[None, :])
    cleared_ok = bool(out[0])
    ctx.note("wrong-answer.result", wrong=wrong, cleared_ok=cleared_ok)
    return {"wrong_answers": wrong, "n_calls": n_calls,
            "cleared_ok": cleared_ok}


def _wrong_safety(ctx, obs):
    inv.require(obs["wrong_answers"] == 0,
                f"{obs['wrong_answers']}/{obs['n_calls']} corrupted "
                f"verify answers were ACCEPTED — silent signature "
                f"acceptance")
    # the chaos really corrupted answers and the spot check caught them
    inv.metric_increased(ctx, "crypto_spot_check_mismatches",
                         until="faulted")
    inv.no_silent_acceptance(ctx)


def _wrong_liveness(ctx, obs):
    inv.completed(obs, "cleared_ok",
                  "verify service after wrong-answer storm cleared")


register(
    "device-wrong-answer",
    "a silently-corrupting device flips verify lanes; the per-call spot "
    "check must catch every corruption (DeviceFault, fallback re-serve) "
    "so no wrong answer is ever returned",
    safety=[("no-silent-acceptance", _wrong_safety)],
    liveness=[("service-after-clear", _wrong_liveness)],
    smoke=True)(_device_wrong_answer)


# ===========================================================================
# stale-commit-replay / partial-commit-replay (stress)
# ===========================================================================

N_REPLAY_BLOCKS = 24


def _commit_replay_body(ctx, mode: str):
    chain_id = f"chaos-{mode}-replay"
    with _python_backend():
        privs, vs = fixtures.make_validators(4, seed=5)
        gen = fixtures.make_genesis(chain_id, privs)
        hashes = fixtures.kvstore_app_hashes(N_REPLAY_BLOCKS)
        chain = fixtures.build_chain(privs, vs, chain_id, N_REPLAY_BLOCKS,
                                     app_hashes=hashes)
        heights = injectors.plan_heights(ctx, f"{mode}-heights",
                                         3, N_REPLAY_BLOCKS - 2, k=3)
        byz_sw, _, _ = harness.fastsync_source(chain_id, chain, gen,
                                               moniker="byz")
        injectors.tamper_block_server(ctx, byz_sw, chain, mode, heights)
        honest_sw, _, honest_store = harness.fastsync_source(
            chain_id, chain, gen, moniker="honest")
        sync_sw, bc, _cons, sync_store = harness.fastsync_syncer(
            chain_id, gen, batch_size=4)
        evicted: list = []
        orig_evict = bc.pool.on_evict
        bc.pool.on_evict = lambda p, r: (evicted.append(p),
                                         orig_evict and orig_evict(p, r))
        for sw in (byz_sw, honest_sw, sync_sw):
            sw.start()
        try:
            connect_switches(sync_sw, byz_sw)
            connect_switches(sync_sw, honest_sw)
            honest_id = honest_sw.node_info.id
            synced = harness.wait_until(
                lambda: sync_store.height >= N_REPLAY_BLOCKS - 1,
                timeout=60)
            chain_ok = all(
                sync_store.load_block(h).hash()
                == honest_store.load_block(h).hash()
                for h in range(1, min(sync_store.height,
                                      N_REPLAY_BLOCKS - 2) + 1))
        finally:
            for sw in (byz_sw, honest_sw, sync_sw):
                sw.stop()
    ctx.note("replay.result", mode=mode, synced_height=sync_store.height,
             evicted=[p[:12] for p in evicted])
    return {"synced": synced, "chain_ok": chain_ok,
            "honest_evicted": honest_id in evicted,
            "synced_height": sync_store.height,
            "pool_status": bc.pool.status()}


def _replay_safety(ctx, obs):
    inv.require(obs["chain_ok"],
                "a replayed commit was accepted: synced chain diverges "
                "from the honest chain")


def _replay_safety_blame(ctx, obs):
    inv.require(not obs["honest_evicted"],
                "the honest peer was evicted for the byzantine peer's "
                "replayed commits")


def _replay_liveness(ctx, obs):
    inv.completed(obs, "synced",
                  f"fast-sync past replayed commits "
                  f"(status {obs['pool_status']})")


for _mode, _desc in (
        ("stale", "a byzantine block server splices OLDER seen-commits "
                  "into served blocks (stale finality proofs, PoTE); "
                  "the syncer must reject them, evict the liar, and "
                  "finish byte-identical from the honest peer"),
        ("partial", "a byzantine block server prunes served LastCommits "
                    "below +2/3 (partial-commit replay, ACE); same "
                    "rejection contract, and the honest peer that "
                    "served the preceding block must not be blamed")):
    register(
        f"{_mode}-commit-replay", _desc,
        safety=[("replayed-commit-rejected", _replay_safety),
                ("honest-peer-spared", _replay_safety_blame)],
        liveness=[("sync-completes", _replay_liveness)],
        smoke=False)(
            (lambda m: lambda ctx: _commit_replay_body(ctx, m))(_mode))


# ===========================================================================
# partition-heal (stress)
# ===========================================================================

def _partition_heal(ctx):
    chain_id = "chaos-partition"
    window_s = 2.0
    with _python_backend():
        nodes, _privs = harness.reactor_net(chain_id, 4, fuzz=True, seed=6)
        victim_i = ctx.rng("partition").randrange(4)
        ctx.plan("partition", victim=victim_i, window_s=window_s,
                 direction="inbound")
        victim = nodes[victim_i]
        others = [nd for i, nd in enumerate(nodes) if i != victim_i]
        try:
            nodes[0].mempool.check_tx(b"chaos=partition")
            pre_ok = harness.wait_until(
                lambda: all(nd.block_store.height >= 2 for nd in nodes),
                timeout=60)
            h_victim0 = victim.block_store.height
            # one-directional: the victim goes deaf (its reads stall) but
            # keeps speaking — the asymmetric-fuzz partition shape
            injectors.sever_inbound(ctx, victim.fuzz_links(), stall=1.0,
                                    label=f"node{victim_i}")
            time.sleep(window_s)
            h_others_mid = max(nd.block_store.height for nd in others)
            injectors.restore(ctx, victim.fuzz_links(),
                              label=f"node{victim_i}")
            healed = harness.wait_until(
                lambda: victim.block_store.height >= h_others_mid + 1,
                timeout=90)
            quorum_ok = harness.wait_until(
                lambda: max(nd.block_store.height
                            for nd in others) > h_others_mid,
                timeout=60)
            h_victim1 = victim.block_store.height
        finally:
            for nd in nodes:
                nd.stop()
    ctx.note("partition.result", pre_ok=pre_ok, healed=healed,
             heights=[nd.block_store.height for nd in nodes])
    return {"pre_ok": pre_ok, "healed": healed, "quorum_ok": quorum_ok,
            "h_victim_before_heal": h_victim0,
            "h_victim_after_heal": h_victim1,
            "_stores": [nd.block_store for nd in nodes]}


def _partition_safety(ctx, obs):
    inv.no_conflicting_commits(obs["_stores"])


def _partition_liveness(ctx, obs):
    inv.completed(obs, "pre_ok", "pre-partition convergence")
    inv.completed(obs, "quorum_ok",
                  "quorum progress during/after the partition")
    inv.completed(obs, "healed", "victim catch-up after heal")
    inv.height_progressed("partitioned node", obs["h_victim_before_heal"],
                          obs["h_victim_after_heal"], min_delta=1)


register(
    "partition-heal",
    "a seed-chosen node is partitioned one-directionally (deaf, still "
    "speaking) via asymmetric fuzz profiles; the 3-node quorum keeps "
    "committing, and after heal the victim catches up with no "
    "conflicting commits",
    safety=[("no-conflicting-commits", _partition_safety)],
    liveness=[("heal-and-catch-up", _partition_liveness)],
    smoke=False)(_partition_heal)


# ===========================================================================
# crash-restart-storm (stress)
# ===========================================================================

def _crash_restart_storm(ctx):
    chain_id = "chaos-crashstorm"
    rng = ctx.rng("crash")
    deltas = [rng.randrange(2, 5) for _ in range(2)]
    ctx.plan("crash-schedule", deltas=deltas)
    home = tempfile.mkdtemp(prefix="chaos-crash-")
    wal_path = os.path.join(home, "data", "cs.wal")
    prefix_hashes: dict[int, bytes] = {}
    stable = True
    target = 0
    for cycle, delta in enumerate(deltas):
        target += delta
        node = harness.solo_node(home, chain_id)
        node.start()
        try:
            reached = harness.wait_until(
                lambda: node.block_store.height >= target, timeout=60)
            if reached:
                # read the committed prefix while the node is live
                # (stop() may close the sqlite stores)
                for h in range(1, target + 1):
                    bh = node.block_store.load_block(h).hash()
                    if h in prefix_hashes and prefix_hashes[h] != bh:
                        stable = False
                    prefix_hashes[h] = bh
            height_now = node.block_store.height
        finally:
            node.stop()
        if not reached:
            ctx.note("crash.stall", cycle=cycle, target=target,
                     height=height_now)
            return {"progressed": False, "prefix_stable": stable,
                    "final_height": height_now, "last_target": target}
        injectors.tear_wal_tail(ctx, wal_path, rng)
        ctx.note("crash.cycle", cycle=cycle, height=target)
    # final restart: must replay past the torn tail and keep going
    node = harness.solo_node(home, chain_id)
    node.start()
    try:
        progressed = harness.wait_until(
            lambda: node.block_store.height >= target + 2, timeout=60)
        final_height = node.block_store.height
        for h in range(1, target + 1):
            if prefix_hashes[h] != node.block_store.load_block(h).hash():
                stable = False
    finally:
        node.stop()
    report = WAL.fsck(wal_path)
    ctx.note("crash.final", final_height=final_height,
             fsck_records=report["records"],
             tail_garbage=bool(report["tail_garbage"]))
    return {"progressed": progressed, "prefix_stable": stable,
            "final_height": final_height, "last_target": target,
            "wal_records": report["records"]}


def _crash_safety(ctx, obs):
    inv.require(obs["prefix_stable"],
                "a restart rewrote an already-committed block — the "
                "chain prefix changed across crash cycles")


def _crash_liveness(ctx, obs):
    inv.completed(obs, "progressed",
                  f"height progress after the crash storm (reached "
                  f"{obs['final_height']}, needed "
                  f"{obs['last_target'] + 2})")


register(
    "crash-restart-storm",
    "SIGKILL-style teardown mid-WAL-write (torn frames appended at "
    "seed-chosen heights), twice; every restart must replay past the "
    "torn tail, never rewrite a committed block, and keep committing",
    safety=[("committed-prefix-stable", _crash_safety)],
    liveness=[("progress-after-restarts", _crash_liveness)],
    smoke=False)(_crash_restart_storm)


SMOKE_ORDER = ["device-wrong-answer", "evidence-flood",
               "byz-equivocation", "device-rung-walk"]
