"""The shipped scenario catalogue.

Each scenario composes injectors from `injectors.py` over a rig from
`harness.py`, returns an observations dict, and registers at least one
safety and one liveness invariant.  Smoke scenarios (`smoke=True`) are
the fast subset tier-1 runs on every push; the rest are the
`faults`-marked stress tier (`tests/test_scenarios_slow.py`).

Adversary models for the fast-sync scenarios follow the deterministic-
finality literature: stale finality proofs (PoTE, arXiv:2512.09409) and
partial-commit replay (ACE, arXiv:2603.10242) — a byzantine block
server re-presenting yesterday's commit, or a quorum certificate pruned
below +2/3, for blocks it wants a syncing node to accept.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time

import numpy as np

from tendermint_tpu.config import P2PConfig, test_config
from tendermint_tpu.consensus import messages as CM
from tendermint_tpu.consensus.reactor import VOTE_CHANNEL
from tendermint_tpu.consensus.wal import WAL
from tendermint_tpu.crypto import backend as cb
from tendermint_tpu.crypto.backend import PythonBackend
from tendermint_tpu.crypto.supervised import CLOSED, SupervisedBackend
from tendermint_tpu.p2p import transport
from tendermint_tpu.p2p.peer import Reactor
from tendermint_tpu.p2p.switch import connect_switches, make_switch
from tendermint_tpu.p2p.types import ChannelDescriptor, NetAddress
from tendermint_tpu.scenarios import fixtures, harness, injectors
from tendermint_tpu.scenarios import invariants as inv
from tendermint_tpu.scenarios.engine import register
from tendermint_tpu.state.evidence import EvidencePool
from tendermint_tpu.utils import chaos as chaosmod
from tendermint_tpu.utils.db import MemDB
from tendermint_tpu.utils.metrics import REGISTRY


# ===========================================================================
# byz-equivocation (smoke)
# ===========================================================================

def _byz_equivocation(ctx):
    chain_id = "chaos-equivocation"
    target = 4
    nodes, _privs, _gen = harness.wire_net(chain_id, 4, seed=1)
    byz = nodes[0]
    heights = injectors.plan_heights(ctx, "equivocation",
                                     1, target + 2, k=3)
    evidence: list = []
    ev_lock = threading.Lock()
    for nd in nodes[1:]:
        nd.cs.evsw.subscribe(
            "scenario", "EvidenceDoubleSign",
            lambda e: (ev_lock.acquire(), evidence.append(e),
                       ev_lock.release()))
    injectors.equivocate(ctx, byz, byz.priv, chain_id, heights)
    for nd in nodes:
        nd.cs.start()
    try:
        nodes[1].mempool.check_tx(b"chaos=equivocation")
        reached = harness.wait_until(
            lambda: all(nd.block_store.height >= target
                        for nd in nodes[1:]), timeout=60)
        captured = harness.wait_until(lambda: bool(evidence),
                                      timeout=20)
    finally:
        for nd in nodes:
            nd.cs.stop()
    with ev_lock:
        ev_count = len(evidence)
        ev_ok = all(
            e.vote_a.validator_address == byz.priv.address
            and e.vote_a.block_id.key() != e.vote_b.block_id.key()
            for e in evidence)
    ctx.note("equivocation.result", evidence=ev_count,
             heights=[nd.block_store.height for nd in nodes])
    return {"reached": reached, "captured": captured,
            "evidence_count": ev_count, "evidence_wellformed": ev_ok,
            "honest_heights": [nd.block_store.height for nd in nodes[1:]],
            "_honest_stores": [nd.block_store for nd in nodes[1:]]}


def _equiv_safety_agreement(ctx, obs):
    inv.no_conflicting_commits(obs["_honest_stores"])


def _equiv_safety_evidence(ctx, obs):
    inv.require(obs["captured"] and obs["evidence_count"] >= 1,
                "honest nodes captured no DuplicateVoteEvidence — the "
                "double votes were accepted silently")
    inv.require(obs["evidence_wellformed"],
                "captured evidence does not accuse the byzantine "
                "validator with conflicting block ids")


def _equiv_liveness(ctx, obs):
    inv.completed(obs, "reached",
                  "honest nodes' height progress under equivocation")


register(
    "byz-equivocation",
    "1 of 4 validators double-signs prevotes at seed-chosen heights; "
    "honest nodes must keep committing identical blocks and capture "
    "DuplicateVoteEvidence",
    safety=[("no-conflicting-commits", _equiv_safety_agreement),
            ("equivocation-evidenced", _equiv_safety_evidence)],
    liveness=[("honest-progress", _equiv_liveness)],
    smoke=True, budget_s=120.0)(_byz_equivocation)


# ===========================================================================
# evidence-flood (smoke)
# ===========================================================================

def _evidence_flood(ctx):
    chain_id = "chaos-evflood"
    privs, vs = fixtures.make_validators(4, seed=2)
    pool = EvidencePool(MemDB(), chain_id)
    real, bogus = injectors.fabricate_evidence(
        ctx, privs, vs, chain_id, n_real=6, n_bogus=18)
    # a solo validator keeps committing while the flood lands
    nodes, _, _ = harness.wire_net(chain_id, 1, seed=3)
    solo = nodes[0]
    solo.cs.start()
    try:
        h_before = solo.block_store.height
        salvo = ([("real", e) for e in real]
                 + [("bogus", e) for e in bogus])
        ctx.rng("flood-order").shuffle(salvo)
        accepted = {"real": 0, "bogus": 0}
        for kind, e in salvo:
            if pool.add(e, vs):
                accepted[kind] += 1
        flood_done_h = solo.block_store.height
        progressed = harness.wait_until(
            lambda: solo.block_store.height >= flood_done_h + 2,
            timeout=30)
        h_after = solo.block_store.height
    finally:
        solo.cs.stop()
    ctx.note("flood.result", accepted=accepted, pool_size=pool.size())
    return {"accepted_real": accepted["real"],
            "accepted_bogus": accepted["bogus"],
            "pool_size": pool.size(), "n_real": len(real),
            "n_bogus": len(bogus), "progressed": progressed,
            "h_before": h_before, "h_after": h_after}


def _flood_safety(ctx, obs):
    inv.require(obs["accepted_bogus"] == 0,
                f"pool accepted {obs['accepted_bogus']} fabricated "
                f"evidence items — forged proofs were silently believed")
    inv.require(obs["accepted_real"] == obs["n_real"]
                and obs["pool_size"] == obs["n_real"],
                f"pool holds {obs['pool_size']} items, expected exactly "
                f"the {obs['n_real']} real proofs "
                f"(accepted_real={obs['accepted_real']})")


def _flood_liveness(ctx, obs):
    inv.completed(obs, "progressed",
                  "solo validator progress during/after evidence flood")
    inv.height_progressed("solo validator", obs["h_before"],
                          obs["h_after"], min_delta=2)


register(
    "evidence-flood",
    "a pool is flooded with fabricated equivocation proofs (strangers, "
    "agreeing votes, torn signatures) mixed with real ones; only the "
    "real ones may land, and consensus keeps committing",
    safety=[("only-valid-evidence", _flood_safety)],
    liveness=[("commit-progress", _flood_liveness)],
    smoke=True, budget_s=60.0)(_evidence_flood)


# ===========================================================================
# device-rung-walk (smoke)
# ===========================================================================

N_RUNGWALK_BLOCKS = 48


def _device_rung_walk(ctx):
    chain_id = "chaos-rungwalk"
    spec = "raise:every=18"
    ctx.plan("crypto-chaos", spec=spec)
    # the programmatic TM_CHAOS_CRYPTO path: install the validated config
    # and let the supervisor pick it up via CryptoChaos.current()
    chaosmod.install(chaosmod.ChaosConfig(seed=ctx.seed, crypto=spec))
    privs, vs = fixtures.make_validators(4, seed=4)
    gen = fixtures.make_genesis(chain_id, privs)
    hashes = fixtures.kvstore_app_hashes(N_RUNGWALK_BLOCKS)
    chain = fixtures.build_chain(privs, vs, chain_id,
                                 N_RUNGWALK_BLOCKS, app_hashes=hashes)
    src_sw, _, src_store = harness.fastsync_source(chain_id, chain, gen)
    sync_sw, bc, _cons, sync_store = harness.fastsync_syncer(
        chain_id, gen, batch_size=2)
    sup = SupervisedBackend(
        [("dev", PythonBackend()), ("python", PythonBackend())],
        breaker_threshold=1, breaker_cooldown_s=0.2,
        retries=0, call_timeout_s=30.0)
    evicted: list = []
    orig_evict = bc.pool.on_evict
    bc.pool.on_evict = lambda p, r: (evicted.append(p),
                                     orig_evict and orig_evict(p, r))
    trips0 = REGISTRY.crypto_breaker_trips.value
    recov0 = REGISTRY.crypto_breaker_recoveries.value
    old = cb._current
    cb._current = sup
    src_sw.start(); sync_sw.start()
    try:
        connect_switches(sync_sw, src_sw)
        deadline = time.time() + 90
        snapped = False
        while (sync_store.height < N_RUNGWALK_BLOCKS - 1
               and time.time() < deadline):
            if (REGISTRY.crypto_breaker_trips.value > trips0
                    and sup.chaos is not None and sup.chaos.active):
                # fault storm "clears" after the first trip; from
                # here the half-open probe must restore the rung
                ctx.snapshot_metrics("faulted")
                snapped = True
                sup.chaos.active = False
                ctx.note("chaos.cleared", mode=sup.chaos.mode)
            time.sleep(0.02)
        if not snapped:
            ctx.snapshot_metrics("faulted")
        synced = sync_store.height >= N_RUNGWALK_BLOCKS - 1
        # drive half-open probes until the breaker recovers
        from tendermint_tpu.crypto import pure_ed25519 as ref
        seed32 = bytes(32)
        pub = np.frombuffer(ref.pubkey_from_seed(seed32), np.uint8)
        msg = np.zeros(32, np.uint8)
        sig = np.frombuffer(ref.sign(seed32, msg.tobytes()), np.uint8)
        deadline = time.time() + 10
        while (REGISTRY.crypto_breaker_recoveries.value == recov0
               and time.time() < deadline):
            sup.verify_batch(pub[None, :], msg[None, :], sig[None, :])
            time.sleep(0.05)
        recovered = (REGISTRY.crypto_breaker_recoveries.value > recov0
                     and sup._rungs[0].state == CLOSED)
        chain_ok = all(
            sync_store.load_block(h).hash()
            == src_store.load_block(h).hash()
            for h in range(1, min(sync_store.height,
                                  N_RUNGWALK_BLOCKS - 2) + 1))
        app_hash_ok = bc.state.app_hash == hashes[-1]
    finally:
        src_sw.stop(); sync_sw.stop()
        cb._current = old
    status = sup.supervisor_status()
    ctx.note("rungwalk.result", synced_height=sync_store.height,
             recovered=recovered, active_rung=status.get("active_rung"),
             evicted=evicted)
    return {"synced": synced, "recovered": recovered,
            "chain_ok": chain_ok, "app_hash_ok": app_hash_ok,
            "evicted": evicted, "synced_height": sync_store.height}


def _rungwalk_safety(ctx, obs):
    inv.no_silent_acceptance(ctx)
    inv.require(obs["chain_ok"] and obs["app_hash_ok"],
                "synced state diverged from the source under device "
                f"faults (chain_ok={obs['chain_ok']}, "
                f"app_hash_ok={obs['app_hash_ok']})")


def _rungwalk_safety_no_blame(ctx, obs):
    inv.require(not obs["evicted"],
                f"peers evicted for OUR injected device faults: "
                f"{obs['evicted']}")


def _rungwalk_liveness(ctx, obs):
    inv.completed(obs, "synced", "fast-sync under device-fault storm")
    inv.metric_increased(ctx, "blocks_synced")


def _rungwalk_liveness_recovery(ctx, obs):
    inv.metric_increased(ctx, "crypto_breaker_trips")
    inv.require(obs["recovered"],
                "device rung never recovered (breaker stayed open) "
                "after the fault storm cleared")


register(
    "device-rung-walk",
    "sustained device faults during fast-sync force supervised-ladder "
    "demotion; the breaker trips, the sync completes on fallback rungs "
    "with byte-identical state, and the rung recovers once faults clear",
    safety=[("no-silent-acceptance", _rungwalk_safety),
            ("no-peer-blame", _rungwalk_safety_no_blame)],
    liveness=[("sync-completes", _rungwalk_liveness),
              ("rung-recovers", _rungwalk_liveness_recovery)],
    smoke=True, budget_s=180.0)(_device_rung_walk)


# ===========================================================================
# device-wrong-answer (smoke)
# ===========================================================================

def _device_wrong_answer(ctx):
    spec = "wrong:lanes=1,every=3"
    ctx.plan("crypto-chaos", spec=spec)
    chaosmod.install(chaosmod.ChaosConfig(seed=ctx.seed, crypto=spec))
    sup = SupervisedBackend(
        [("dev", PythonBackend()), ("python", PythonBackend())],
        breaker_threshold=3, breaker_cooldown_s=0.1,
        retries=0, call_timeout_s=30.0, spot_check_every=1)
    from tendermint_tpu.crypto import pure_ed25519 as ref
    rng = ctx.rng("vectors")
    n_calls = 12
    ctx.plan("verify-calls", n=n_calls)
    wrong = 0
    for i in range(n_calls):
        seed32 = bytes(rng.randrange(256) for _ in range(32))
        msg = bytes(rng.randrange(256) for _ in range(32))
        good = rng.randrange(2) == 0
        sig = ref.sign(seed32, msg)
        if not good:
            sig = sig[:-1] + bytes([sig[-1] ^ 1])
        pub = np.frombuffer(ref.pubkey_from_seed(seed32), np.uint8)
        out = sup.verify_batch(pub[None, :],
                               np.frombuffer(msg, np.uint8)[None, :],
                               np.frombuffer(sig, np.uint8)[None, :])
        if bool(out[0]) != good:
            wrong += 1
    ctx.snapshot_metrics("faulted")
    if sup.chaos is not None:
        sup.chaos.active = False
    # after the storm clears the device rung must serve clean answers
    seed32 = bytes(32)
    msg = bytes(32)
    sig = ref.sign(seed32, msg)
    pub = np.frombuffer(ref.pubkey_from_seed(seed32), np.uint8)
    out = sup.verify_batch(pub[None, :],
                           np.frombuffer(msg, np.uint8)[None, :],
                           np.frombuffer(sig, np.uint8)[None, :])
    cleared_ok = bool(out[0])
    ctx.note("wrong-answer.result", wrong=wrong, cleared_ok=cleared_ok)
    return {"wrong_answers": wrong, "n_calls": n_calls,
            "cleared_ok": cleared_ok}


def _wrong_safety(ctx, obs):
    inv.require(obs["wrong_answers"] == 0,
                f"{obs['wrong_answers']}/{obs['n_calls']} corrupted "
                f"verify answers were ACCEPTED — silent signature "
                f"acceptance")
    # the chaos really corrupted answers and the spot check caught them
    inv.metric_increased(ctx, "crypto_spot_check_mismatches",
                         until="faulted")
    inv.no_silent_acceptance(ctx)


def _wrong_liveness(ctx, obs):
    inv.completed(obs, "cleared_ok",
                  "verify service after wrong-answer storm cleared")


register(
    "device-wrong-answer",
    "a silently-corrupting device flips verify lanes; the per-call spot "
    "check must catch every corruption (DeviceFault, fallback re-serve) "
    "so no wrong answer is ever returned",
    safety=[("no-silent-acceptance", _wrong_safety)],
    liveness=[("service-after-clear", _wrong_liveness)],
    smoke=True, budget_s=30.0)(_device_wrong_answer)


# ===========================================================================
# stale-commit-replay / partial-commit-replay (stress)
# ===========================================================================

N_REPLAY_BLOCKS = 24


def _commit_replay_body(ctx, mode: str):
    chain_id = f"chaos-{mode}-replay"
    privs, vs = fixtures.make_validators(4, seed=5)
    gen = fixtures.make_genesis(chain_id, privs)
    hashes = fixtures.kvstore_app_hashes(N_REPLAY_BLOCKS)
    chain = fixtures.build_chain(privs, vs, chain_id, N_REPLAY_BLOCKS,
                                 app_hashes=hashes)
    heights = injectors.plan_heights(ctx, f"{mode}-heights",
                                     3, N_REPLAY_BLOCKS - 2, k=3)
    byz_sw, _, _ = harness.fastsync_source(chain_id, chain, gen,
                                           moniker="byz")
    injectors.tamper_block_server(ctx, byz_sw, chain, mode, heights)
    honest_sw, _, honest_store = harness.fastsync_source(
        chain_id, chain, gen, moniker="honest")
    sync_sw, bc, _cons, sync_store = harness.fastsync_syncer(
        chain_id, gen, batch_size=4)
    evicted: list = []
    orig_evict = bc.pool.on_evict
    bc.pool.on_evict = lambda p, r: (evicted.append(p),
                                     orig_evict and orig_evict(p, r))
    for sw in (byz_sw, honest_sw, sync_sw):
        sw.start()
    try:
        t_sync0 = time.time()
        connect_switches(sync_sw, byz_sw)
        connect_switches(sync_sw, honest_sw)
        honest_id = honest_sw.node_info.id
        synced = harness.wait_until(
            lambda: sync_store.height >= N_REPLAY_BLOCKS - 1,
            timeout=60)
        sync_s = max(time.time() - t_sync0, 1e-6)
        chain_ok = all(
            sync_store.load_block(h).hash()
            == honest_store.load_block(h).hash()
            for h in range(1, min(sync_store.height,
                                  N_REPLAY_BLOCKS - 2) + 1))
    finally:
        for sw in (byz_sw, honest_sw, sync_sw):
            sw.stop()
    ctx.note("replay.result", mode=mode, synced_height=sync_store.height,
             evicted=[p[:12] for p in evicted])
    return {"synced": synced, "chain_ok": chain_ok,
            "honest_evicted": honest_id in evicted,
            "synced_height": sync_store.height,
            "pool_status": bc.pool.status(),
            "budget_metrics": {
                "sync_blocks_per_sec": round(sync_store.height / sync_s,
                                             3)}}


def _replay_safety(ctx, obs):
    inv.require(obs["chain_ok"],
                "a replayed commit was accepted: synced chain diverges "
                "from the honest chain")


def _replay_safety_blame(ctx, obs):
    inv.require(not obs["honest_evicted"],
                "the honest peer was evicted for the byzantine peer's "
                "replayed commits")


def _replay_liveness(ctx, obs):
    inv.completed(obs, "synced",
                  f"fast-sync past replayed commits "
                  f"(status {obs['pool_status']})")


for _mode, _desc in (
        ("stale", "a byzantine block server splices OLDER seen-commits "
                  "into served blocks (stale finality proofs, PoTE); "
                  "the syncer must reject them, evict the liar, and "
                  "finish byte-identical from the honest peer"),
        ("partial", "a byzantine block server prunes served LastCommits "
                    "below +2/3 (partial-commit replay, ACE); same "
                    "rejection contract, and the honest peer that "
                    "served the preceding block must not be blamed")):
    register(
        f"{_mode}-commit-replay", _desc,
        safety=[("replayed-commit-rejected", _replay_safety),
                ("honest-peer-spared", _replay_safety_blame)],
        liveness=[("sync-completes", _replay_liveness)],
        smoke=False, budget_s=180.0,
        budgets={"sync_blocks_per_sec": {"min": 0.2}})(
            (lambda m: lambda ctx: _commit_replay_body(ctx, m))(_mode))


# ===========================================================================
# partition-heal (stress)
# ===========================================================================

def _partition_heal(ctx):
    chain_id = "chaos-partition"
    window_s = 2.0
    nodes, _privs = harness.reactor_net(chain_id, 4, fuzz=True, seed=6)
    victim_i = ctx.rng("partition").randrange(4)
    ctx.plan("partition", victim=victim_i, window_s=window_s,
             direction="inbound")
    victim = nodes[victim_i]
    others = [nd for i, nd in enumerate(nodes) if i != victim_i]
    try:
        nodes[0].mempool.check_tx(b"chaos=partition")
        pre_ok = harness.wait_until(
            lambda: all(nd.block_store.height >= 2 for nd in nodes),
            timeout=60)
        h_victim0 = victim.block_store.height
        # one-directional: the victim goes deaf (its reads stall) but
        # keeps speaking — the asymmetric-fuzz partition shape
        injectors.sever_inbound(ctx, victim.fuzz_links(), stall=1.0,
                                label=f"node{victim_i}")
        time.sleep(window_s)
        h_others_mid = max(nd.block_store.height for nd in others)
        injectors.restore(ctx, victim.fuzz_links(),
                          label=f"node{victim_i}")
        t_heal0 = time.time()
        healed = harness.wait_until(
            lambda: victim.block_store.height >= h_others_mid + 1,
            timeout=90)
        heal_lag_s = time.time() - t_heal0
        quorum_ok = harness.wait_until(
            lambda: max(nd.block_store.height
                        for nd in others) > h_others_mid,
            timeout=60)
        h_victim1 = victim.block_store.height
    finally:
        for nd in nodes:
            nd.stop()
    ctx.note("partition.result", pre_ok=pre_ok, healed=healed,
             heights=[nd.block_store.height for nd in nodes])
    return {"pre_ok": pre_ok, "healed": healed, "quorum_ok": quorum_ok,
            "h_victim_before_heal": h_victim0,
            "h_victim_after_heal": h_victim1,
            "_stores": [nd.block_store for nd in nodes],
            "budget_metrics": {"victim_heal_lag_s": round(heal_lag_s, 3)}}


def _partition_safety(ctx, obs):
    inv.no_conflicting_commits(obs["_stores"])


def _partition_liveness(ctx, obs):
    inv.completed(obs, "pre_ok", "pre-partition convergence")
    inv.completed(obs, "quorum_ok",
                  "quorum progress during/after the partition")
    inv.completed(obs, "healed", "victim catch-up after heal")
    inv.height_progressed("partitioned node", obs["h_victim_before_heal"],
                          obs["h_victim_after_heal"], min_delta=1)


register(
    "partition-heal",
    "a seed-chosen node is partitioned one-directionally (deaf, still "
    "speaking) via asymmetric fuzz profiles; the 3-node quorum keeps "
    "committing, and after heal the victim catches up with no "
    "conflicting commits",
    safety=[("no-conflicting-commits", _partition_safety)],
    liveness=[("heal-and-catch-up", _partition_liveness)],
    smoke=False, budget_s=240.0,
    budgets={"victim_heal_lag_s": {"max": 60.0}})(_partition_heal)


# ===========================================================================
# crash-restart-storm (stress)
# ===========================================================================

def _crash_restart_storm(ctx):
    chain_id = "chaos-crashstorm"
    rng = ctx.rng("crash")
    deltas = [rng.randrange(2, 5) for _ in range(2)]
    ctx.plan("crash-schedule", deltas=deltas)
    home = tempfile.mkdtemp(prefix="chaos-crash-")
    wal_path = os.path.join(home, "data", "cs.wal")
    prefix_hashes: dict[int, bytes] = {}
    stable = True
    target = 0
    for cycle, delta in enumerate(deltas):
        target += delta
        node = harness.solo_node(home, chain_id)
        node.start()
        try:
            reached = harness.wait_until(
                lambda: node.block_store.height >= target, timeout=60)
            if reached:
                # read the committed prefix while the node is live
                # (stop() may close the sqlite stores)
                for h in range(1, target + 1):
                    bh = node.block_store.load_block(h).hash()
                    if h in prefix_hashes and prefix_hashes[h] != bh:
                        stable = False
                    prefix_hashes[h] = bh
            height_now = node.block_store.height
        finally:
            node.stop()
        if not reached:
            ctx.note("crash.stall", cycle=cycle, target=target,
                     height=height_now)
            return {"progressed": False, "prefix_stable": stable,
                    "final_height": height_now, "last_target": target}
        injectors.tear_wal_tail(ctx, wal_path, rng)
        ctx.note("crash.cycle", cycle=cycle, height=target)
    # final restart: must replay past the torn tail and keep going
    node = harness.solo_node(home, chain_id)
    node.start()
    t_restart0 = time.time()
    try:
        progressed = harness.wait_until(
            lambda: node.block_store.height >= target + 2, timeout=60)
        post_restart_s = time.time() - t_restart0
        final_height = node.block_store.height
        for h in range(1, target + 1):
            if prefix_hashes[h] != node.block_store.load_block(h).hash():
                stable = False
    finally:
        node.stop()
    report = WAL.fsck(wal_path)
    ctx.note("crash.final", final_height=final_height,
             fsck_records=report["records"],
             tail_garbage=bool(report["tail_garbage"]))
    return {"progressed": progressed, "prefix_stable": stable,
            "final_height": final_height, "last_target": target,
            "wal_records": report["records"],
            "budget_metrics": {
                "post_restart_progress_s": round(post_restart_s, 3)}}


def _crash_safety(ctx, obs):
    inv.require(obs["prefix_stable"],
                "a restart rewrote an already-committed block — the "
                "chain prefix changed across crash cycles")


def _crash_liveness(ctx, obs):
    inv.completed(obs, "progressed",
                  f"height progress after the crash storm (reached "
                  f"{obs['final_height']}, needed "
                  f"{obs['last_target'] + 2})")


register(
    "crash-restart-storm",
    "SIGKILL-style teardown mid-WAL-write (torn frames appended at "
    "seed-chosen heights), twice; every restart must replay past the "
    "torn tail, never rewrite a committed block, and keep committing",
    safety=[("committed-prefix-stable", _crash_safety)],
    liveness=[("progress-after-restarts", _crash_liveness)],
    smoke=False, budget_s=300.0,
    budgets={"post_restart_progress_s": {"max": 45.0}})(_crash_restart_storm)


# ===========================================================================
# combined-adversary scenarios (stress): multiple concurrently-running
# injectors with seed-derived phase offsets, via ctx.schedule()
# ===========================================================================

def _tcp_source_p2p():
    """P2PConfig for a dialable fast-sync source: a real TCP listener on
    an ephemeral port, so the syncer can dial it as a PERSISTENT peer
    and the self-healing reconnect path (jittered backoff after a
    partition-induced eviction) is in play."""
    p2p = test_config().p2p
    p2p.laddr = "tcp://127.0.0.1:0"
    # WAN-ish bandwidth: at 512KB/s a whole test chain lands in the
    # pool's 75-deep request window within ~100ms and a mid-sync
    # partition has nothing left to starve.  20KB/s keeps requests
    # outstanding for seconds (blocks are ~2.6KB) while staying 2x above
    # the pool's 10KB/s starvation floor during healthy flow.
    p2p.send_rate = 20_480
    return p2p


def _sever_window(ctx, sync_sw, peer_id: str, window_s: float,
                  stall: float, label: str) -> None:
    """Asymmetric partition of ONE link for `window_s`: every read the
    syncer does on its link to `peer_id` stalls.  The profile is
    re-applied every 50ms because the self-healing reconnect path keeps
    establishing FRESH links (new FuzzedConnection, clean profile) —
    a partition severs the path, not one connection object."""
    ctx.note("partition.sever", label=label, window_s=window_s)
    deadline = time.time() + window_s
    while time.time() < deadline:
        link = harness.fuzz_link_to(sync_sw, peer_id)
        if link is not None:
            link.set_profile(read_drop_prob=1.0, read_stall=stall)
        time.sleep(0.05)
    link = harness.fuzz_link_to(sync_sw, peer_id)
    if link is not None:
        link.set_profile(read_drop_prob=0.0)
    ctx.note("partition.heal", label=label)


# ---------------------------------------------------------------------------
# device-storm-partition
# ---------------------------------------------------------------------------

N_STORM_BLOCKS = 32
N_STORM_VALIDATORS = 12


def _device_storm_partition(ctx):
    chain_id = "chaos-storm-partition"
    spec = "raise:every=6"
    ctx.plan("crypto-chaos", spec=spec)
    chaosmod.install(chaosmod.ChaosConfig(seed=ctx.seed, crypto=spec))
    privs, vs = fixtures.make_validators(N_STORM_VALIDATORS, seed=8)
    gen = fixtures.make_genesis(chain_id, privs)
    hashes = fixtures.kvstore_app_hashes(N_STORM_BLOCKS)
    chain = fixtures.build_chain(privs, vs, chain_id, N_STORM_BLOCKS,
                                 app_hashes=hashes)
    src_sw, _, src_store = harness.fastsync_source(
        chain_id, chain, gen, moniker="source",
        config=_tcp_source_p2p())
    sync_sw, bc, _cons, sync_store = harness.fastsync_syncer(
        chain_id, gen, batch_size=4, fuzz=True)
    sup = SupervisedBackend(
        [("dev", PythonBackend()), ("python", PythonBackend())],
        breaker_threshold=1, breaker_cooldown_s=0.2,
        retries=0, call_timeout_s=30.0)
    trips0 = REGISTRY.crypto_breaker_trips.value
    old = cb._current
    cb._current = sup
    src_sw.start(); sync_sw.start()
    src_id = src_sw.node_info.id
    # the window must outlast the pool's 3s request timeout, and the
    # stall must outlast the window, or reads merely slow down and
    # no eviction (hence no reconnect) ever fires
    window_s = 4.5
    ctx.plan("partition-window", window_s=window_s)
    try:
        sync_sw.dial_peer_async(
            NetAddress.parse(str(src_sw._listener.addr)),
            persistent=True)
        connected = harness.wait_until(
            lambda: sync_sw.get_peer(src_id) is not None, timeout=15)

        def partition():
            # sever only after blocks flowed, so the stall is a real
            # mid-sync partition (and the pool's starvation eviction
            # can fire against a peer that HAS delivered)
            harness.wait_until(lambda: sync_store.height >= 4,
                               timeout=30)
            _sever_window(ctx, sync_sw, src_id, window_s, 6.0,
                          "syncer<-source")

        def storm_clear():
            # the device-fault storm clears only after it provably
            # hit (first breaker trip), like a real transient fault
            harness.wait_until(
                lambda: REGISTRY.crypto_breaker_trips.value > trips0,
                timeout=45)
            if sup.chaos is not None:
                sup.chaos.active = False
            ctx.note("chaos.cleared")

        sched = ctx.schedule("storm")
        sched.add("partition", partition, after=0.2, jitter_s=0.5)
        sched.add("device-storm-clear", storm_clear, after=0.5,
                  jitter_s=1.0)
        sched.run(join_timeout_s=90.0)
        t_sync0 = time.time()
        synced = harness.wait_until(
            lambda: sync_store.height >= N_STORM_BLOCKS - 1,
            timeout=120)
        sync_s = max(time.time() - t_sync0, 1e-6)
        chain_ok = all(
            sync_store.load_block(h).hash()
            == src_store.load_block(h).hash()
            for h in range(1, min(sync_store.height,
                                  N_STORM_BLOCKS - 2) + 1))
        src_banned = sync_sw.is_banned(src_id)
        src_score = sync_sw.misbehavior_score(src_id)
    finally:
        src_sw.stop(); sync_sw.stop()
        cb._current = old
    ctx.note("storm-partition.result", synced_height=sync_store.height,
             src_banned=src_banned, src_score=src_score)
    return {"connected": connected, "synced": synced, "chain_ok": chain_ok,
            "src_banned": src_banned, "src_score": src_score,
            "synced_height": sync_store.height,
            "budget_metrics": {
                "sync_blocks_per_sec": round(sync_store.height / sync_s, 3)}}


def _storm_safety(ctx, obs):
    inv.no_silent_acceptance(ctx)
    inv.require(obs["chain_ok"],
                "synced chain diverged from the source under the "
                "combined device-fault + partition storm")


def _storm_safety_no_blame(ctx, obs):
    inv.require(not obs["src_banned"] and obs["src_score"] == 0.0,
                f"the honest source was blamed for OUR injected faults "
                f"(banned={obs['src_banned']}, score={obs['src_score']}) "
                f"— partitions and device faults must never score a peer")


def _storm_liveness(ctx, obs):
    inv.completed(obs, "connected", "initial persistent dial")
    inv.completed(obs, "synced",
                  "fast-sync through the device storm + partition")
    inv.metric_increased(ctx, "blocks_synced")


def _storm_liveness_evidence(ctx, obs):
    inv.metric_increased(ctx, "crypto_breaker_trips")
    inv.metric_increased(ctx, "switch_reconnect_attempts")


register(
    "device-storm-partition",
    "12-validator fast-sync under a COMBINED adversary: a device-fault "
    "storm (breaker trips to fallback rungs) concurrent with an "
    "asymmetric partition of the source link; the evicted source heals "
    "via jittered persistent reconnect and the sync finishes "
    "byte-identical with the source unblamed",
    safety=[("no-silent-acceptance", _storm_safety),
            ("no-peer-blame", _storm_safety_no_blame)],
    liveness=[("sync-completes", _storm_liveness),
              ("storm-and-heal-evidenced", _storm_liveness_evidence)],
    smoke=False, budget_s=240.0,
    budgets={"sync_blocks_per_sec": {"min": 0.1}})(_device_storm_partition)


# ---------------------------------------------------------------------------
# equivocation-crash-restart
# ---------------------------------------------------------------------------

N_ECR_VALIDATORS = 10

# a 10-node net on pure-python crypto needs ~1s of GIL-shared verify
# work per height; the test_config 20-100ms windows would burn every
# height on round timeouts
ECR_TIMEOUTS = {"timeout_propose": 3.0, "timeout_propose_delta": 1.0,
                "timeout_prevote": 1.5, "timeout_prevote_delta": 0.5,
                "timeout_precommit": 1.5, "timeout_precommit_delta": 0.5}


def _equivocation_crash_restart(ctx):
    chain_id = "chaos-equiv-crash"
    # autostart=False: the equivocation hook and evidence watchers
    # must install before height 1, or a fast net blows past the
    # scheduled double-sign heights unobserved
    nodes, privs = harness.reactor_net(chain_id, N_ECR_VALIDATORS,
                                       seed=7, timeouts=ECR_TIMEOUTS,
                                       autostart=False)
    gen = nodes[0].gen
    byz = nodes[0]
    victim_i = 1 + ctx.rng("victim").randrange(N_ECR_VALIDATORS - 1)
    ctx.plan("crash-victim", index=victim_i)
    heights = injectors.plan_heights(ctx, "equivocation", 2, 6, k=2)
    evidence: list = []
    ev_lock = threading.Lock()
    watchers = [i for i in range(1, N_ECR_VALIDATORS)
                if i != victim_i][:2]
    for i in watchers:
        nodes[i].cs.evsw.subscribe(
            "scenario", "EvidenceDoubleSign",
            lambda e: (ev_lock.acquire(), evidence.append(e),
                       ev_lock.release()))
    # in reactor nets votes travel only via the per-peer gossip
    # routines, which pull from the node's own vote sets — a
    # conflicting vote is rejected from the set and never gossiped.
    # The injector must push it onto the wire itself.
    injectors.equivocate(
        ctx, byz, privs[0], chain_id, heights,
        broadcast=lambda msg: byz.switch.broadcast(
            VOTE_CHANNEL, CM.encode_msg(msg)))
    harness.start_reactor_net(nodes, stagger_s=0.02)
    holder = {"victim": nodes[victim_i]}
    crashed = threading.Event()
    quorum = [nd for i, nd in enumerate(nodes)
              if i not in (0, victim_i)]
    try:
        nodes[1].mempool.check_tx(b"chaos=equiv-crash")
        pre_ok = harness.wait_until(
            lambda: all(nd.block_store.height >= 2 for nd in nodes),
            timeout=180)
        h_mid = max(nd.block_store.height for nd in quorum)

        def crash():
            ctx.note("crash.stop", index=victim_i,
                     height=holder["victim"].block_store.height)
            holder["victim"].stop()
            crashed.set()

        def restart():
            # the offsets order restart after crash; the event makes
            # the ordering hard even under scheduler skew
            crashed.wait(timeout=60)
            node2 = harness.ReactorNode(
                privs[victim_i], gen, chain_id, f"node{victim_i}-r",
                cfg=harness.config_with_timeouts(ECR_TIMEOUTS))
            node2.start()
            for i, nd in enumerate(nodes):
                if i != victim_i:
                    connect_switches(node2.switch, nd.switch)
            holder["victim"] = node2
            ctx.note("crash.restarted", index=victim_i)

        sched = ctx.schedule("crash-restart")
        sched.add("crash", crash, after=0.1, jitter_s=0.5)
        sched.add("restart", restart, after=1.5, jitter_s=1.0)
        sched.run(join_timeout_s=120.0)
        progressed = harness.wait_until(
            lambda: max(nd.block_store.height
                        for nd in quorum) >= h_mid + 2, timeout=180)
        h_quorum = max(nd.block_store.height for nd in quorum)
        # the restarted validator rebuilt from GENESIS: catching up
        # to the quorum proves consensus catchup gossip serves the
        # whole committed prefix to a from-scratch joiner
        t_catchup0 = time.time()
        caught_up = harness.wait_until(
            lambda: holder["victim"].block_store.height >= h_quorum,
            timeout=180)
        catchup_s = time.time() - t_catchup0
        captured = harness.wait_until(lambda: bool(evidence),
                                      timeout=30)
    finally:
        for i, nd in enumerate(nodes):
            if i != victim_i:
                nd.stop()
        holder["victim"].stop()
    with ev_lock:
        ev_count = len(evidence)
        ev_ok = all(
            e.vote_a.validator_address == privs[0].address
            and e.vote_a.block_id.key() != e.vote_b.block_id.key()
            for e in evidence)
    ctx.note("equiv-crash.result", pre_ok=pre_ok, progressed=progressed,
             caught_up=caught_up, evidence=ev_count,
             victim_height=holder["victim"].block_store.height)
    return {"pre_ok": pre_ok, "progressed": progressed,
            "caught_up": caught_up, "captured": captured,
            "evidence_count": ev_count, "evidence_wellformed": ev_ok,
            "victim_height": holder["victim"].block_store.height,
            "quorum_height": h_quorum,
            "budget_metrics": {"victim_catchup_s": round(catchup_s, 3)},
            "_stores": ([nd.block_store for nd in quorum]
                        + [holder["victim"].block_store])}


def _ecr_safety_agreement(ctx, obs):
    inv.no_conflicting_commits(obs["_stores"])


def _ecr_safety_evidence(ctx, obs):
    inv.require(obs["captured"] and obs["evidence_count"] >= 1,
                "no DuplicateVoteEvidence captured — the equivocation "
                "ran unobserved through the crash-restart storm")
    inv.require(obs["evidence_wellformed"],
                "captured evidence does not accuse the byzantine "
                "validator with conflicting block ids")


def _ecr_liveness(ctx, obs):
    inv.completed(obs, "pre_ok", "pre-crash convergence of all 10 nodes")
    inv.completed(obs, "progressed",
                  "quorum progress while the victim was down and the "
                  "byzantine node kept double-signing")


def _ecr_liveness_catchup(ctx, obs):
    inv.completed(
        obs, "caught_up",
        f"restarted-from-genesis validator catch-up (victim at "
        f"{obs['victim_height']}, quorum at {obs['quorum_height']})")


register(
    "equivocation-crash-restart",
    "10-validator reactor net under a COMBINED adversary: one validator "
    "double-signs at seed-chosen heights while another crashes and is "
    "rebuilt from genesis mid-equivocation; the quorum keeps committing "
    "identical blocks, captures the evidence, and the restarted node "
    "catches up over catchup gossip",
    safety=[("no-conflicting-commits", _ecr_safety_agreement),
            ("equivocation-evidenced", _ecr_safety_evidence)],
    liveness=[("quorum-progress", _ecr_liveness),
              ("restart-catch-up", _ecr_liveness_catchup)],
    smoke=False, budget_s=420.0,
    budgets={"victim_catchup_s": {"max": 150.0}})(_equivocation_crash_restart)


# ---------------------------------------------------------------------------
# stale-replay-partition
# ---------------------------------------------------------------------------

N_SRP_BLOCKS = 24
N_SRP_VALIDATORS = 12


def _stale_replay_partition(ctx):
    chain_id = "chaos-stale-partition"
    privs, vs = fixtures.make_validators(N_SRP_VALIDATORS, seed=9)
    gen = fixtures.make_genesis(chain_id, privs)
    hashes = fixtures.kvstore_app_hashes(N_SRP_BLOCKS)
    chain = fixtures.build_chain(privs, vs, chain_id, N_SRP_BLOCKS,
                                 app_hashes=hashes)
    # a contiguous stale band guarantees the byzantine server gets
    # asked for at least one tampered height no matter how the pool
    # splits the request window between the two sources
    h0 = 8 + ctx.rng("stale-band").randrange(N_SRP_BLOCKS - 14)
    band = list(range(h0, h0 + 4))
    byz_sw, _, _ = harness.fastsync_source(chain_id, chain, gen,
                                           moniker="byz")
    injectors.tamper_block_server(ctx, byz_sw, chain, "stale", band)
    honest_sw, _, honest_store = harness.fastsync_source(
        chain_id, chain, gen, moniker="honest",
        config=_tcp_source_p2p())
    sync_sw, bc, _cons, sync_store = harness.fastsync_syncer(
        chain_id, gen, batch_size=4, fuzz=True)
    evicted: list = []
    orig_evict = bc.pool.on_evict
    bc.pool.on_evict = lambda p, r: (evicted.append((p, r)),
                                     orig_evict and orig_evict(p, r))
    for sw in (byz_sw, honest_sw, sync_sw):
        sw.start()
    honest_id = honest_sw.node_info.id
    byz_id = byz_sw.node_info.id
    # outlast the pool's 3s request timeout so the honest peer is
    # provably evicted-then-reconnected (see _sever_window)
    window_s = 4.5
    ctx.plan("partition-window", window_s=window_s)
    try:
        connect_switches(sync_sw, byz_sw)
        sync_sw.dial_peer_async(
            NetAddress.parse(str(honest_sw._listener.addr)),
            persistent=True)
        connected = harness.wait_until(
            lambda: sync_sw.get_peer(honest_id) is not None,
            timeout=15)

        def partition():
            # engage before verification reaches the stale band, so
            # the redo path has to ride out the honest-link blackout
            harness.wait_until(lambda: sync_store.height >= 3,
                               timeout=30)
            _sever_window(ctx, sync_sw, honest_id, window_s, 6.0,
                          "syncer<-honest")

        def delay_byz():
            link = harness.fuzz_link_to(sync_sw, byz_id)
            if link is not None:
                injectors.delay_storm(ctx, [link], delay_prob=0.3,
                                      max_delay=0.03, label="byz-link")

        sched = ctx.schedule("stale-partition")
        sched.add("sever-honest", partition, after=0.2, jitter_s=0.4)
        sched.add("delay-byz", delay_byz, after=0.1, jitter_s=0.3)
        sched.run(join_timeout_s=90.0)
        t_sync0 = time.time()
        synced = harness.wait_until(
            lambda: sync_store.height >= N_SRP_BLOCKS - 1, timeout=120)
        sync_s = max(time.time() - t_sync0, 1e-6)
        chain_ok = all(
            sync_store.load_block(h).hash()
            == honest_store.load_block(h).hash()
            for h in range(1, min(sync_store.height,
                                  N_SRP_BLOCKS - 2) + 1))
        byz_banned = sync_sw.is_banned(byz_id)
        honest_banned = sync_sw.is_banned(honest_id)
        honest_score = sync_sw.misbehavior_score(honest_id)
    finally:
        for sw in (byz_sw, honest_sw, sync_sw):
            sw.stop()
    byz_bad_block = any(p == byz_id and r.startswith("bad block")
                        for p, r in evicted)
    ctx.note("stale-partition.result", synced_height=sync_store.height,
             byz_banned=byz_banned, honest_banned=honest_banned,
             evicted=[(p[:12], r) for p, r in evicted])
    return {"connected": connected, "synced": synced, "chain_ok": chain_ok,
            "byz_banned": byz_banned, "byz_bad_block": byz_bad_block,
            "honest_banned": honest_banned, "honest_score": honest_score,
            "synced_height": sync_store.height,
            "budget_metrics": {
                "sync_blocks_per_sec": round(sync_store.height / sync_s, 3)}}


def _srp_safety(ctx, obs):
    inv.require(obs["chain_ok"],
                "a stale replayed commit was accepted behind the "
                "partition: synced chain diverges from the honest chain")
    inv.require(obs["byz_bad_block"] and obs["byz_banned"],
                f"the stale-replay server was not banned "
                f"(bad_block_evicted={obs['byz_bad_block']}, "
                f"banned={obs['byz_banned']}) — a proven commit lie must "
                f"ban immediately")


def _srp_safety_no_blame(ctx, obs):
    inv.require(not obs["honest_banned"] and obs["honest_score"] == 0.0,
                f"the honest source was blamed for partition-induced "
                f"timeouts (banned={obs['honest_banned']}, "
                f"score={obs['honest_score']}) — slow is not malicious")


def _srp_liveness(ctx, obs):
    inv.completed(obs, "connected", "initial persistent dial")
    inv.completed(obs, "synced",
                  "fast-sync past the stale band and the partition")
    inv.metric_increased(ctx, "blocks_synced")


def _srp_liveness_heal(ctx, obs):
    inv.metric_increased(ctx, "switch_reconnect_attempts")
    inv.metric_increased(ctx, "switch_peers_evicted")


register(
    "stale-replay-partition",
    "12-validator fast-sync under a COMBINED adversary: a byzantine "
    "server replays a band of stale commits while an asymmetric "
    "partition blacks out the honest link and a delay storm jitters the "
    "byzantine one; the liar is banned on the first proven bad block, "
    "the timeout-evicted honest peer reconnects unblamed, and the sync "
    "finishes byte-identical",
    safety=[("stale-band-rejected-liar-banned", _srp_safety),
            ("honest-peer-spared", _srp_safety_no_blame)],
    liveness=[("sync-completes", _srp_liveness),
              ("self-healing-evidenced", _srp_liveness_heal)],
    smoke=False, budget_s=240.0,
    budgets={"sync_blocks_per_sec": {"min": 0.1}})(_stale_replay_partition)


# ---------------------------------------------------------------------------
# partition-heal-25
# ---------------------------------------------------------------------------

N_HEAL_NODES = 25
N_HEAL_VICTIMS = 5
HEAL_BAN_WINDOW_S = 3.0


class _MeshProbeReactor(Reactor):
    """One-channel probe reactor for the p2p-layer rig: counts received
    probes so a post-heal broadcast proves the reconnected mesh carries
    traffic, not just registry entries."""

    CH = 0x70

    def __init__(self):
        super().__init__()
        self.probes = 0
        self._lock = threading.Lock()

    def get_channels(self):
        return [ChannelDescriptor(id=self.CH)]

    def receive(self, ch_id, peer, msg):
        with self._lock:
            self.probes += 1


def _heal_p2p_config() -> P2PConfig:
    # short backoff so the 25-node storm rides through several jittered
    # attempts inside the scenario budget; ban window likewise compressed
    return P2PConfig(laddr="tcp://127.0.0.1:0", pex=False,
                     max_num_peers=N_HEAL_NODES - 1,
                     dial_timeout_s=2.0,
                     reconnect_max_attempts=60,
                     reconnect_backoff_base_s=0.5,
                     reconnect_backoff_max_s=2.0,
                     misbehavior_ban_window_s=HEAL_BAN_WINDOW_S)


def _partition_heal_25(ctx):
    """p2p-layer partition-heal at 25 validators: a seed-chosen minority
    is cut off (listeners down, cross links severed); the persistent
    dialers on the majority side must heal the full mesh through
    jittered exponential backoff without ever overshooting
    max_num_peers, and a peer banned for misbehavior mid-run must stay
    out for the whole window before rejoining."""
    rng = ctx.rng("heal25")
    victims = sorted(rng.sample(range(N_HEAL_NODES), N_HEAL_VICTIMS))
    survivors = [i for i in range(N_HEAL_NODES) if i not in victims]
    liar, reporter = rng.sample(survivors, 2)
    window_s = 4.0
    ctx.plan("partition", victims=victims, window_s=window_s)
    ctx.plan("misbehavior", liar=liar, reporter=reporter,
             ban_window_s=HEAL_BAN_WINDOW_S)

    reactors = [_MeshProbeReactor() for _ in range(N_HEAL_NODES)]
    switches = [make_switch("chaos-heal25", {"probe": reactors[i]},
                            _heal_p2p_config(), moniker=f"node{i}")
                for i in range(N_HEAL_NODES)]
    overshoot = {"max": 0}
    stop_sampling = threading.Event()

    def sample():
        while not stop_sampling.is_set():
            m = max(sw.n_peers() for sw in switches)
            if m > overshoot["max"]:
                overshoot["max"] = m
            time.sleep(0.02)

    def dialer_of(i: int, j: int) -> int:
        # cross-cut edges dial FROM the survivor side, so a severed
        # minority models a true partition (nobody inside it can dial
        # out); the liar->reporter edge is dialed by the liar so its
        # post-ban redials exercise the refused-while-banned path
        iv, jv = i in victims, j in victims
        if iv != jv:
            return j if iv else i
        if {i, j} == {liar, reporter}:
            return liar
        return min(i, j)

    try:
        for sw in switches:
            sw.start()
            time.sleep(0.01)            # staggered bring-up
        addrs = [sw._listener.addr for sw in switches]
        ids = [sw.node_info.id for sw in switches]
        threading.Thread(target=sample, daemon=True,
                         name="heal25-sampler").start()
        for i in range(N_HEAL_NODES):
            for j in range(i + 1, N_HEAL_NODES):
                d = dialer_of(i, j)
                other = j if d == i else i
                switches[d].dial_peer_async(addrs[other], persistent=True)
        meshed = harness.wait_until(
            lambda: all(sw.n_peers() == N_HEAL_NODES - 1
                        for sw in switches), timeout=90)
        ctx.note("heal25.meshed", ok=meshed)

        victim_ids = {ids[v] for v in victims}
        ports = {v: addrs[v].port for v in victims}
        severed = threading.Event()

        def sever():
            for v in victims:
                switches[v]._listener.close()
            for s in survivors:
                for p in switches[s].peers():
                    if p.id in victim_ids:
                        p.mconn.conn.close()
            severed.set()
            ctx.note("heal25.severed", victims=victims)

        def heal():
            # offsets order heal after sever; the event makes the
            # ordering hard even under scheduler skew
            severed.wait(timeout=30)
            time.sleep(window_s)
            for v in victims:
                # the accept routine re-reads _listener every loop, so
                # swapping in a fresh listener on the same port reopens
                # the victim to the survivors' backoff dialers
                switches[v]._listener = transport.Listener(
                    NetAddress("tcp", "127.0.0.1", ports[v]))
            ctx.note("heal25.healed")

        sched = ctx.schedule("partition-heal")
        sched.add("sever", sever, after=0.1, jitter_s=0.2)
        sched.add("heal", heal, after=0.2, jitter_s=0.2)
        sched.run(join_timeout_s=60.0)

        t_heal0 = time.time()
        reconverged = harness.wait_until(
            lambda: all(sw.n_peers() == N_HEAL_NODES - 1
                        for sw in switches), timeout=120)
        reconverge_s = time.time() - t_heal0
        if not reconverged:
            ctx.note("heal25.stragglers",
                     peer_counts=[sw.n_peers() for sw in switches])
        probe_reach = len(switches[reporter].broadcast(
            _MeshProbeReactor.CH, b"heal-probe"))
        probe_rcvd = harness.wait_until(
            lambda: sum(r.probes for r in reactors) >= N_HEAL_NODES - 1,
            timeout=15)

        rep = switches[reporter]
        liar_id = ids[liar]
        crossed = rep.report_misbehavior(
            liar_id, "scenario: proven commit lie", ban=True)
        time.sleep(1.2)
        ban_held = rep.is_banned(liar_id) and rep.get_peer(liar_id) is None
        if not ban_held:
            ctx.note("heal25.ban-leak",
                     is_banned=rep.is_banned(liar_id),
                     liar_registered=rep.get_peer(liar_id) is not None,
                     reporter_peers=rep.n_peers())
        restored = harness.wait_until(
            lambda: rep.get_peer(liar_id) is not None, timeout=30)
        unbanned = not rep.is_banned(liar_id)
    finally:
        stop_sampling.set()
        for sw in switches:
            sw.stop()
    ctx.note("heal25.result", meshed=meshed, reconverged=reconverged,
             overshoot_max=overshoot["max"], probe_reach=probe_reach,
             ban_held=ban_held, restored=restored)
    return {"meshed": meshed, "reconverged": reconverged,
            "overshoot_max": overshoot["max"],
            "probe_reach": probe_reach, "probe_rcvd": probe_rcvd,
            "crossed": crossed, "ban_held": ban_held,
            "restored": restored, "unbanned": unbanned,
            "budget_metrics": {
                "mesh_reconverge_s": round(reconverge_s, 3)}}


def _heal25_safety_cap(ctx, obs):
    inv.require(obs["overshoot_max"] <= N_HEAL_NODES - 1,
                f"peer count overshot max_num_peers during the heal "
                f"storm (max seen {obs['overshoot_max']} > "
                f"{N_HEAL_NODES - 1})")


def _heal25_safety_ban(ctx, obs):
    inv.require(obs["crossed"],
                "ban=True misbehavior report did not cross the ban line")
    inv.require(obs["ban_held"],
                "a banned misbehaving peer was re-admitted (or never "
                "evicted) inside its ban window")


def _heal25_liveness(ctx, obs):
    inv.completed(obs, "meshed", "initial 25-node full mesh")
    inv.completed(obs, "reconverged",
                  "post-heal reconvergence to the full mesh")
    inv.require(obs["probe_reach"] == N_HEAL_NODES - 1
                and obs["probe_rcvd"],
                f"post-heal broadcast reached {obs['probe_reach']}/"
                f"{N_HEAL_NODES - 1} peers — reconnected entries exist "
                f"but the mesh is not carrying traffic")
    inv.metric_increased(ctx, "switch_reconnect_attempts")


def _heal25_liveness_ban_expiry(ctx, obs):
    inv.completed(obs, "restored",
                  "banned peer rejoining after its window expired")
    inv.require(obs["unbanned"],
                "ban did not self-expire after its configured window")
    inv.metric_increased(ctx, "switch_peers_evicted")


register(
    "partition-heal-25",
    "p2p self-healing at scale: a 25-validator TCP mesh loses a "
    "seed-chosen 5-node minority (listeners down, links cut); jittered "
    "persistent-reconnect backoff heals the full mesh with no peer-count "
    "overshoot past max_num_peers, and a peer banned for misbehavior "
    "stays out for the whole window before rejoining",
    safety=[("no-peer-overshoot", _heal25_safety_cap),
            ("ban-holds-for-window", _heal25_safety_ban)],
    liveness=[("mesh-reconverges", _heal25_liveness),
              ("ban-expires-and-rejoins", _heal25_liveness_ban_expiry)],
    smoke=False, budget_s=300.0,
    budgets={"mesh_reconverge_s": {"max": 100.0}})(_partition_heal_25)


SMOKE_ORDER = ["device-wrong-answer", "evidence-flood",
               "byz-equivocation", "device-rung-walk",
               "snapshot-torn-tail", "batchplane-isolation",
               "eviction-storm"]
