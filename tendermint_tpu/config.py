"""Nested configuration with defaults and fast test variants.

Reference: `config/config.go` — Config{Base, RPC, P2P, Mempool, Consensus}
(`:12-21`), defaults (`:57-132`), consensus timeouts (`:364-381`), test
variants with memdb + 10ms timeouts (`:34-42,384-396`).  TOML scaffolding
in `tendermint_tpu.cli` (reference `config/toml.go`).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class BaseConfig:
    chain_id: str = ""
    home: str = "~/.tendermint_tpu"
    proxy_app: str = "kvstore"           # registry name or tcp:// addr
    moniker: str = "anonymous"
    fast_sync: bool = True
    db_backend: str = "sqlite"           # sqlite | memdb
    log_level: str = "info"
    # tpu | python | native; TM_CRYPTO_BACKEND env overrides the default
    # (same knob `crypto.backend.get_backend` honors standalone) — a
    # config-file value or --crypto-backend flag still wins over both
    crypto_backend: str = field(
        default_factory=lambda: os.environ.get("TM_CRYPTO_BACKEND", "tpu"))

    def root(self) -> str:
        return os.path.expanduser(self.home)

    def genesis_file(self) -> str:
        return os.path.join(self.root(), "genesis.json")

    def priv_validator_file(self) -> str:
        return os.path.join(self.root(), "priv_validator.json")

    def db_dir(self) -> str:
        return os.path.join(self.root(), "data")


@dataclass
class RPCConfig:
    laddr: str = "tcp://0.0.0.0:26657"
    grpc_laddr: str = ""
    unsafe: bool = False


@dataclass
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    seeds: list[str] = field(default_factory=list)
    persistent_peers: list[str] = field(default_factory=list)
    max_num_peers: int = 50
    pex: bool = True
    send_rate: int = 512_000             # B/s (reference p2p/connection.go:31)
    recv_rate: int = 512_000
    flush_throttle_ms: int = 100
    handshake_timeout_s: float = 20.0
    dial_timeout_s: float = 3.0
    fuzz: bool = False
    # FuzzedConnection profile when fuzz=True (write-direction drop +
    # both-direction delay; the RNG seed is derived from the installed
    # ChaosConfig scenario seed — see p2p/fuzz.py)
    fuzz_drop_prob: float = 0.05
    fuzz_delay_prob: float = 0.1
    fuzz_max_delay: float = 0.05
    # persistent-peer reconnect: exponential backoff capped in SECONDS
    # (reference p2p/switch.go reconnectToPeer), a separate attempt cap,
    # and ±jitter_frac jitter so a healed partition doesn't thundering-
    # herd every dialer onto the same instant
    reconnect_max_attempts: int = 16
    reconnect_backoff_base_s: float = 1.0
    reconnect_backoff_max_s: float = 32.0
    reconnect_jitter_frac: float = 0.2
    # peer misbehavior scoring (p2p/switch.py): strikes accumulate per
    # peer id (across reconnects); at ban_score the peer is evicted and
    # refused in dial/accept for ban_window_s
    misbehavior_ban_score: float = 3.0
    misbehavior_ban_window_s: float = 30.0


@dataclass
class MempoolConfig:
    recheck: bool = True
    broadcast: bool = True
    wal_dir: str = ""
    cache_size: int = 100_000            # reference mempool/mempool.go:51
    # admission control (mempool/mempool.py): hard caps on resident txs
    # and bytes — at the cap a new tx is admitted only by evicting
    # strictly lower-priority txs (lowest-priority-oldest first), else
    # rejected with ERR_MEMPOOL_FULL; 0 disables a cap
    max_txs: int = 5_000                 # reference config.go Mempool.Size
    max_bytes: int = 1_073_741_824       # 1 GiB resident tx bytes
    # reject-before-verify backpressure: refuse enveloped txs outright
    # while the batch plane's mempool class already queues this many
    # lanes, so a signature flood sheds at the front door instead of
    # growing the verify queue under the consensus class; 0 disables
    backpressure_lanes: int = 4_096


@dataclass
class ConsensusConfig:
    wal_dir: str = ""
    wal_light: bool = False
    # reference config/config.go:364-381 (ms)
    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    timeout_commit: float = 1.0
    skip_timeout_commit: bool = False
    # Multiplicative per-round timeout growth on top of the reference's
    # linear deltas (reference config/config.go:365-381 grows linearly
    # only; growth 1.0 = exact reference behavior).  When the transport
    # or scheduler delay that kills rounds is unknown a priori, linear
    # growth needs delay/delta rounds to catch up, each costing a full
    # failed round; a factor > 1 overtakes ANY bounded delay in
    # O(log(delay)) rounds.  Off by default; the scheduler-sabotage
    # stress tier enables it.
    timeout_round_growth: float = 1.0
    timeout_max: float = 30.0            # cap for the exponential form
    max_block_size_txs: int = 10_000
    create_empty_blocks: bool = True
    create_empty_blocks_interval: float = 0.0

    def _grown(self, base: float, delta: float, round_: int) -> float:
        t = base + delta * round_
        g = self.timeout_round_growth
        if g > 1.0:
            # growth^round overflows float for round ~1750 at g=1.5; the
            # cap is reached long before that, so clamp the exponent to
            # the first round where base*g^r alone exceeds the cap.
            # base may legitimately be 0 (a test config that skips a
            # step instantly) — guard the division so the clamp math
            # can't ZeroDivisionError, growth then reaches the cap fast
            import math
            base_ = max(base, 1e-9)
            max_r = math.ceil(math.log(max(self.timeout_max / base_, 1.0),
                                       g)) + 1
            t = min(t * g ** min(round_, max_r), self.timeout_max)
        return t

    def propose_timeout(self, round_: int) -> float:
        return self._grown(self.timeout_propose,
                           self.timeout_propose_delta, round_)

    def prevote_timeout(self, round_: int) -> float:
        return self._grown(self.timeout_prevote,
                           self.timeout_prevote_delta, round_)

    def precommit_timeout(self, round_: int) -> float:
        return self._grown(self.timeout_precommit,
                           self.timeout_precommit_delta, round_)


@dataclass
class CryptoConfig:
    """Supervised-crypto knobs (crypto/supervised.py).  `supervised`
    wraps `base.crypto_backend` in the fault-tolerant ladder; the rest
    tune its breaker/timeout/retry/spot-check behavior.  TM_CRYPTO_*
    env vars override these when the supervisor is built standalone."""
    supervised: bool = field(
        default_factory=lambda: os.environ.get(
            "TM_CRYPTO_SUPERVISED", "") not in ("", "0", "false"))
    breaker_threshold: int = 3       # consecutive faults before trip
    breaker_cooldown_s: float = 30.0  # OPEN -> HALF-OPEN delay
    call_timeout_s: float = 60.0     # per device call; 0 disables
    retries: int = 1                 # same-rung retries before fallback
    spot_check_every: int = 0        # 0 = off; N = re-check 1 lane of
    #                                  every Nth device verify on the ref


@dataclass
class Config:
    base: BaseConfig = field(default_factory=BaseConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    crypto: CryptoConfig = field(default_factory=CryptoConfig)


def default_config() -> Config:
    return Config()


def test_config() -> Config:
    """Fast in-memory config (reference `config/config.go:384-396`)."""
    c = Config()
    c.base.db_backend = "memdb"
    c.base.crypto_backend = "python"
    c.base.fast_sync = False
    # deltas keep the reference's growth ratio (~1/6 of base per round,
    # config/config.go:365-371): failed rounds must lengthen enough that
    # a loaded scheduler self-heals instead of churning rounds for
    # minutes (the r3 stress-tier finding)
    c.consensus.timeout_propose = 0.1
    c.consensus.timeout_propose_delta = 0.02
    c.consensus.timeout_prevote = 0.02
    c.consensus.timeout_prevote_delta = 0.01
    c.consensus.timeout_precommit = 0.02
    c.consensus.timeout_precommit_delta = 0.01
    c.consensus.timeout_commit = 0.02
    c.consensus.skip_timeout_commit = True
    # failed rounds grow exponentially (healthy rounds stay 100ms): at
    # fixed linear deltas a loaded single-core host can outpace the
    # timeout growth every round and churn nil rounds for the whole test
    # budget (the stress tier proved the mode; in-process reactor nets
    # under full-suite load hit it too, just rarer)
    c.consensus.timeout_round_growth = 1.5
    c.consensus.timeout_max = 5.0
    return c


# --- config file (TOML; reference config/toml.go + viper binding) ---------

_SECTIONS = ("base", "rpc", "p2p", "mempool", "consensus", "crypto")


def config_file(root: str) -> str:
    return os.path.join(root, "config.toml")


def save_config_file(cfg: Config, path: str) -> None:
    """Write the full config as TOML so a testnet ships one file per node
    (reference `config/toml.go` writes config.toml at init)."""
    def fmt(v) -> str:
        if isinstance(v, bool):
            return "true" if v else "false"
        if isinstance(v, (int, float)):
            return repr(v)
        if isinstance(v, list):
            return "[" + ", ".join(fmt(x) for x in v) + "]"
        return '"' + str(v).replace("\\", "\\\\").replace('"', '\\"') + '"'

    lines = ["# tendermint_tpu configuration (TOML)", ""]
    for sec in _SECTIONS:
        lines.append(f"[{sec}]")
        obj = getattr(cfg, sec)
        for k, v in vars(obj).items():
            lines.append(f"{k} = {fmt(v)}")
        lines.append("")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write("\n".join(lines))
    os.replace(tmp, path)


def load_config_file(path: str, cfg: Config | None = None) -> Config:
    """Overlay a TOML config file onto defaults.  Unknown keys fail loudly
    (a typo silently reverting to a default is how testnets lose nights)."""
    try:
        import tomllib               # 3.11+ stdlib
    except ModuleNotFoundError:      # 3.10: same API under the old name
        import tomli as tomllib
    cfg = cfg or Config()
    with open(path, "rb") as f:
        data = tomllib.load(f)
    for sec, kv in data.items():
        if sec not in _SECTIONS:
            raise ValueError(f"unknown config section [{sec}] in {path}")
        obj = getattr(cfg, sec)
        for k, v in kv.items():
            if not hasattr(obj, k):
                raise ValueError(f"unknown config key {sec}.{k} in {path}")
            cur = getattr(obj, k)
            if isinstance(cur, float) and isinstance(v, int) \
                    and not isinstance(v, bool):
                v = float(v)
            if isinstance(v, bool) and not isinstance(cur, bool):
                raise ValueError(     # bool IS an int in Python; reject
                    f"config key {sec}.{k}: expected "
                    f"{type(cur).__name__}, got bool")
            if cur is not None and not isinstance(v, type(cur)):
                raise ValueError(
                    f"config key {sec}.{k}: expected "
                    f"{type(cur).__name__}, got {type(v).__name__}")
            setattr(obj, k, v)
    return cfg
