"""tmlint convention rules: RPC route gating, span categories, metric
names.

- **route-gating**: any RPC route literally named ``unsafe_*`` or
  ``debug_*`` must be registered only inside the ``config.rpc.unsafe``
  branch (reference `rpc/core/routes.go:30-46` AddUnsafeRoutes).  A
  debug route outside the gate ships the profiler/filesystem surface to
  every client.

- **route-write-containment**: a route handler that writes to the
  filesystem must contain its target path the same way
  ``debug_trace_start`` does — ``os.path.realpath`` + a parent check —
  because route params are attacker-controlled strings.

- **span-category**: a ``span("name")`` literal must either resolve to
  a category via `utils/tracing.default_category` (name-prefix table)
  or carry an explicit ``cat=`` keyword (including
  ``cat=tracing.CAT_NONE`` for deliberately-uncategorized bookkeeping
  spans).  An uncategorized span silently drops out of the attribution
  partition and its wall clock reads as device_idle in the doctor.
  The prefix table covers the consensus timeline plane too:
  ``consensus.*`` and ``telemetry.*`` spans resolve to the
  CAT_CONSENSUS / CAT_TELEMETRY flight-recorder categories.

- **metric-name**: instrument attributes on a metrics registry render
  as ``tendermint_<attr>`` in the Prometheus 0.0.4 exposition; names
  and Vec label names must match the Prometheus grammar, label names
  must not shadow reserved ones, and the generated ``_bucket``/``_sum``
  /``_count`` series must not collide across instruments (a collision
  corrupts the whole scrape).

- **scenario-budget**: every stress-tier scenario registration (a
  ``register(...)`` call carrying ``safety=``/``liveness=`` where
  ``smoke`` is absent or not literally ``True``) must declare at least
  one metric budget via ``budgets={...}``.  A stress rig without a
  budgeted metric only fails on outright invariant violations — a
  fault-path latency regression sails through green, which is exactly
  what the chaos ledger exists to catch.
"""

from __future__ import annotations

import ast
import re

from tendermint_tpu.analysis.core import (FileCtx, Rule, call_name,
                                          register)

_GATED_PREFIXES = ("unsafe_", "debug_")

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_RESERVED_LABELS = {"le", "quantile", "__name__"}

_INSTRUMENT_CTORS = {
    "Counter": (),
    "Gauge": (),
    "Summary": ("_count",),
    "Histogram": ("_bucket", "_sum", "_count"),
    "CounterVec": (),
    "GaugeVec": (),
    "HistogramVec": ("_bucket", "_sum", "_count"),
}

_WRITE_CALLS = {"os.replace", "os.remove", "os.unlink", "os.rename",
                "os.makedirs", "os.mkdir", "os.rmdir", "shutil.rmtree",
                "shutil.copy", "shutil.copyfile", "shutil.move"}


def _str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ---------------------------------------------------------------------------
# route gating
# ---------------------------------------------------------------------------


def _route_registrations(tree: ast.AST):
    """Yield (route_name, key_node, handler_node) for every string key
    of a dict literal that maps route names to handlers — i.e. whose
    values are `self.<method>` attributes (the Routes.table shape)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        pairs = [(k, v) for k, v in zip(node.keys, node.values)
                 if _str_const(k) is not None]
        if not pairs:
            continue
        # route tables map names to bound methods; a dict of string ->
        # string (headers, JSON payloads) is not a route table
        if not all(isinstance(v, ast.Attribute) for _, v in pairs):
            continue
        for k, v in pairs:
            yield _str_const(k), k, v


def _inside_unsafe_branch(node: ast.AST) -> bool:
    """Lexically inside an `if` whose test mentions 'unsafe'."""
    cur = getattr(node, "_tmlint_parent", None)
    while cur is not None:
        if isinstance(cur, ast.If):
            try:
                test_src = ast.unparse(cur.test)
            except Exception:  # pragma: no cover - unparse is total on 3.9+
                test_src = ""
            if "unsafe" in test_src:
                return True
        cur = getattr(cur, "_tmlint_parent", None)
    return False


@register
class RouteGatingRule(Rule):
    name = "route-gating"
    description = ("unsafe_*/debug_* RPC routes must be registered only "
                   "inside the config.rpc.unsafe branch")

    def visit_file(self, ctx: FileCtx):
        for route, key_node, _handler in _route_registrations(ctx.tree):
            if not route.startswith(_GATED_PREFIXES):
                continue
            if not _inside_unsafe_branch(key_node):
                yield ctx.finding(
                    self.name, key_node,
                    f"route '{route}' is named as operator-only but is "
                    f"registered outside the config.rpc.unsafe branch")


@register
class RouteWriteContainmentRule(Rule):
    name = "route-write-containment"
    description = ("route handlers that write files must contain the "
                   "target path (os.path.realpath + parent check), "
                   "since route params are attacker-controlled")

    def visit_file(self, ctx: FileCtx):
        # handler method names referenced from any route table
        handlers: dict[str, ast.AST] = {}
        for _route, key_node, handler in _route_registrations(ctx.tree):
            if (isinstance(handler, ast.Attribute)
                    and isinstance(handler.value, ast.Name)
                    and handler.value.id == "self"):
                handlers.setdefault(handler.attr, key_node)
        if not handlers:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            if node.name not in handlers:
                continue
            writes = self._write_sites(node)
            if not writes:
                continue
            calls = {call_name(c) for c in ast.walk(node)
                     if isinstance(c, ast.Call)}
            if "os.path.realpath" in calls:
                continue
            for w in writes:
                yield ctx.finding(
                    self.name, w,
                    f"route handler '{node.name}' writes to the "
                    f"filesystem without os.path.realpath containment "
                    f"of the target path")

    @staticmethod
    def _write_sites(fn: ast.AST) -> list:
        out = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in _WRITE_CALLS:
                out.append(node)
            elif name in ("open", "io.open"):
                mode = None
                if len(node.args) >= 2:
                    mode = _str_const(node.args[1])
                for kw in node.keywords:
                    if kw.arg == "mode":
                        mode = _str_const(kw.value)
                if mode and any(c in mode for c in "wax+"):
                    out.append(node)
        return out


# ---------------------------------------------------------------------------
# span categories
# ---------------------------------------------------------------------------


@register
class SpanCategoryRule(Rule):
    name = "span-category"
    description = ("span(\"name\") literals must resolve to a "
                   "flight-recorder category (known name prefix, "
                   "including consensus./telemetry.) or carry an "
                   "explicit cat= keyword")

    def visit_file(self, ctx: FileCtx):
        from tendermint_tpu.utils.tracing import default_category
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            is_span = ((isinstance(func, ast.Name) and func.id == "span")
                       or (isinstance(func, ast.Attribute)
                           and func.attr == "span"))
            if not is_span or not node.args:
                continue
            name = _str_const(node.args[0])
            if name is None:
                continue            # dynamic names can't be checked here
            if any(kw.arg == "cat" for kw in node.keywords):
                continue
            if default_category(name) is None:
                yield ctx.finding(
                    self.name, node,
                    f"span '{name}' has no category: its wall clock "
                    f"reads as device_idle in the doctor — use a prefix "
                    f"known to utils/attribution.py or pass cat= "
                    f"(cat=tracing.CAT_NONE for bookkeeping spans)")


# ---------------------------------------------------------------------------
# metric names
# ---------------------------------------------------------------------------


@register
class MetricNameRule(Rule):
    name = "metric-name"
    description = ("registry instruments must render to valid, "
                   "non-colliding Prometheus series names; Vec labels "
                   "must be valid non-reserved label names")

    def visit_file(self, ctx: FileCtx):
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            series: dict[str, ast.AST] = {}
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                ctor = call_name(node.value).rsplit(".", 1)[-1]
                if ctor not in _INSTRUMENT_CTORS:
                    continue
                for tgt in node.targets:
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    yield from self._check_instrument(
                        ctx, tgt.attr, ctor, node.value, series)

    def _check_instrument(self, ctx, attr, ctor, call, series):
        name = f"tendermint_{attr}"
        if not _METRIC_NAME_RE.match(name):
            yield ctx.finding(
                self.name, call,
                f"metric '{name}' is not a valid Prometheus metric name")
        for suffix in ("",) + _INSTRUMENT_CTORS[ctor]:
            full = name + suffix
            if full in series:
                yield ctx.finding(
                    self.name, call,
                    f"metric series '{full}' collides with the one "
                    f"generated by another instrument (corrupts the "
                    f"scrape)")
            series[full] = call
        if ctor in ("CounterVec", "GaugeVec", "HistogramVec") and call.args:
            label = _str_const(call.args[0])
            if label is not None:
                if not _LABEL_NAME_RE.match(label):
                    yield ctx.finding(
                        self.name, call,
                        f"label '{label}' is not a valid Prometheus "
                        f"label name")
                elif label in _RESERVED_LABELS or \
                        label.startswith("__"):
                    yield ctx.finding(
                        self.name, call,
                        f"label '{label}' is reserved in the Prometheus "
                        f"exposition format")


# ---------------------------------------------------------------------------
# scenario metric budgets
# ---------------------------------------------------------------------------


@register
class ScenarioBudgetRule(Rule):
    name = "scenario-budget"
    description = ("stress-tier scenario registrations must declare at "
                   "least one metric budget (budgets={...})")

    def visit_file(self, ctx: FileCtx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node).rsplit(".", 1)[-1] != "register":
                continue
            kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
            # scenario registrations carry both invariant lists — that
            # shape separates them from the analysis-rule @register
            # decorator and any other register() in the tree
            if "safety" not in kwargs or "liveness" not in kwargs:
                continue
            smoke = kwargs.get("smoke")
            if isinstance(smoke, ast.Constant) and smoke.value is True:
                continue                    # smoke tier: budgets optional
            budgets = kwargs.get("budgets")
            empty = (budgets is None
                     or (isinstance(budgets, ast.Constant)
                         and budgets.value is None)
                     or (isinstance(budgets, ast.Dict)
                         and not budgets.keys))
            if empty:
                sc_name = (_str_const(node.args[0])
                           if node.args else None) or "<dynamic>"
                yield ctx.finding(
                    self.name, node,
                    f"stress scenario '{sc_name}' declares no metric "
                    f"budgets: without a budgeted metric a fault-path "
                    f"latency regression reads as green — declare "
                    f"budgets={{\"<metric>\": {{\"max\": ...}}}} and "
                    f"report it in the body's budget_metrics")
