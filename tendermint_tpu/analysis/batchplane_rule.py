"""tmlint rule: hot-path verify producers must ride the batch plane.

- **batchplane-producer**: modules on the verify hot path (``consensus/``,
  ``light/``, ``mempool/``, ``blockchain/``, ``types/``) must submit
  signature-verify work through ``tendermint_tpu.batchplane`` — never
  call ``crypto.backend``'s ``verify_batch`` / ``verify_grouped`` /
  ``verify_grouped_templated[_async]`` directly.  A direct call bypasses
  the shared scheduler: its lanes cannot coalesce with concurrent
  producers, ignore priority classes (a light-client flood would no
  longer yield to consensus votes), and skip the plane's occupancy /
  wait-time accounting, so the doctor's half-full-batch attribution
  under-reports.  The scheduler itself (``batchplane/``), the backend
  ladder (``crypto/``), device layers (``ops/``, ``parallel/``) and the
  bench harness stay direct by design.
"""

from __future__ import annotations

import ast

from tendermint_tpu.analysis.core import (FileCtx, Rule, call_name,
                                          register)

# path prefixes (posix, package-relative) where the rule applies
_PRODUCER_PREFIXES = ("consensus/", "light/", "mempool/", "blockchain/",
                      "types/")

_VERIFY_METHODS = {"verify_batch", "verify_grouped",
                   "verify_grouped_templated",
                   "verify_grouped_templated_async"}

_BACKEND_MODULE = "tendermint_tpu.crypto.backend"


def _backend_aliases(tree: ast.AST) -> tuple[set, set]:
    """(module_aliases, function_names) bound to crypto.backend in this
    file: ``from tendermint_tpu.crypto import backend as cb`` binds the
    alias ``cb``; ``from tendermint_tpu.crypto.backend import
    verify_grouped`` binds the bare function name."""
    mods: set[str] = set()
    fns: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == _BACKEND_MODULE:
                    mods.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.module == "tendermint_tpu.crypto":
                for a in node.names:
                    if a.name == "backend":
                        mods.add(a.asname or "backend")
            elif node.module == _BACKEND_MODULE:
                for a in node.names:
                    if a.name in _VERIFY_METHODS:
                        fns.add(a.asname or a.name)
    return mods, fns


@register
class BatchPlaneProducerRule(Rule):
    name = "batchplane-producer"
    description = ("hot-path producers (consensus/light/mempool/"
                   "blockchain/types) must submit verify work through "
                   "the batch plane, not crypto.backend directly")

    def visit_file(self, ctx: FileCtx):
        rel = ctx.path.replace("\\", "/")
        for pre in ("tendermint_tpu/", "./"):
            if rel.startswith(pre):
                rel = rel[len(pre):]
        if not rel.startswith(_PRODUCER_PREFIXES):
            return
        mods, fns = _backend_aliases(ctx.tree)
        if not mods and not fns:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            hit = None
            if "." in name:
                base, meth = name.rsplit(".", 1)
                if base in mods and meth in _VERIFY_METHODS:
                    hit = name
            elif name in fns:
                hit = name
            if hit:
                yield ctx.finding(
                    self.name, node,
                    f"direct backend call '{hit}' bypasses the batch "
                    f"plane: lanes cannot coalesce with other producers "
                    f"and skip priority/fairness scheduling — submit via "
                    f"tendermint_tpu.batchplane with an explicit "
                    f"producer= and klass=")
