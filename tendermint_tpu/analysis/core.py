"""tmlint core: rule registry, per-file driver, suppressions, baseline.

A rule is a class with a `name`, a `description`, and two hooks:

- ``visit_file(ctx)`` -> findings for one parsed file;
- ``finalize()``      -> findings that need the whole project (the
  lock-order graph spans classes across modules, so cycles can only be
  reported after every file has been visited).

Rules are registered by class (`@register`); each `lint_paths()` call
instantiates them fresh, so a run never sees state from a prior run.

Findings carry a *fingerprint* that is stable across line shifts —
``rule | path | enclosing symbol | message`` hashed — which is what the
committed baseline stores: editing an unrelated part of a file must not
un-grandfather an old finding, and moving a grandfathered finding to a
different function is a new finding on purpose.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field

SCHEMA = "tmlint/1"

# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str            # posix-style path relative to the lint root
    line: int
    col: int
    message: str
    symbol: str = ""     # enclosing `Class.method` / function qualname

    @property
    def fingerprint(self) -> str:
        key = f"{self.rule}|{self.path}|{self.symbol}|{self.message}"
        return hashlib.sha256(key.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "symbol": self.symbol, "fingerprint": self.fingerprint}

    @staticmethod
    def from_dict(d: dict) -> "Finding":
        return Finding(rule=d["rule"], path=d["path"],
                       line=int(d.get("line", 0)), col=int(d.get("col", 0)),
                       message=d["message"], symbol=d.get("symbol", ""))

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: [{self.rule}] "
                f"{self.message}"
                + (f"  ({self.symbol})" if self.symbol else ""))


# ---------------------------------------------------------------------------
# file context handed to rules
# ---------------------------------------------------------------------------


@dataclass
class FileCtx:
    path: str                 # relative, posix separators
    abspath: str
    tree: ast.AST
    lines: list[str]          # source lines (1-based access via line-1)

    def qualname_at(self, node: ast.AST) -> str:
        """Enclosing `Class.method`-style symbol for a node, computed
        from the parent map built at parse time."""
        parts = []
        cur = getattr(node, "_tmlint_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(cur.name)
            cur = getattr(cur, "_tmlint_parent", None)
        return ".".join(reversed(parts))

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(rule=rule, path=self.path,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       message=message, symbol=self.qualname_at(node))


def _link_parents(tree: ast.AST) -> None:
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            child._tmlint_parent = parent


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------


class Rule:
    name = ""
    description = ""

    def visit_file(self, ctx: FileCtx):
        return ()

    def finalize(self):
        return ()


RULE_CLASSES: list[type] = []


def register(cls: type) -> type:
    if not cls.name:
        raise ValueError(f"rule class {cls.__name__} has no name")
    if any(c.name == cls.name for c in RULE_CLASSES):
        raise ValueError(f"duplicate rule name {cls.name!r}")
    RULE_CLASSES.append(cls)
    return cls


def all_rules() -> list[tuple[str, str]]:
    """(name, description) pairs, sorted — the `--list-rules` catalog."""
    return sorted((c.name, c.description) for c in RULE_CLASSES)


# ---------------------------------------------------------------------------
# suppressions: `# tmlint: disable=rule1,rule2` (or `all`) on the line
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*tmlint:\s*disable=([A-Za-z0-9_,\- ]+)")


def suppressions(lines: list[str]) -> dict[int, set[str]]:
    """Per-line suppressed rule-name sets (1-based line numbers).  A
    comment on its own line also covers the NEXT line, so long findings
    can be suppressed without breaking the line-length budget."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        out.setdefault(i, set()).update(rules)
        if line.lstrip().startswith("#"):       # comment-only line
            out.setdefault(i + 1, set()).update(rules)
    return out


def is_suppressed(finding: Finding, suppr: dict[int, set[str]]) -> bool:
    rules = suppr.get(finding.line)
    if not rules:
        return False
    return finding.rule in rules or "all" in rules


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def load_baseline(path: str | None = None) -> set[str]:
    """Fingerprints of grandfathered findings; missing file = empty."""
    p = path or baseline_path()
    if not os.path.exists(p):
        return set()
    with open(p) as f:
        doc = json.load(f)
    return {e["fingerprint"] for e in doc.get("findings", ())}


def save_baseline(findings, path: str | None = None) -> str:
    """Write the baseline for `findings` (sorted, with human-readable
    context next to each fingerprint so review diffs mean something)."""
    p = path or baseline_path()
    entries = sorted(
        ({"fingerprint": f.fingerprint, "rule": f.rule, "path": f.path,
          "symbol": f.symbol, "message": f.message} for f in findings),
        key=lambda e: (e["path"], e["rule"], e["fingerprint"]))
    doc = {"schema": SCHEMA, "findings": entries}
    tmp = p + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, p)
    return p


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)   # not suppressed
    suppressed: int = 0
    files: int = 0
    errors: list[str] = field(default_factory=list)   # unparseable files

    def fresh(self, baseline: set[str]) -> list[Finding]:
        """Findings not covered by the baseline — the ones that fail."""
        return [f for f in self.findings if f.fingerprint not in baseline]

    def to_dict(self, baseline: set[str] | None = None) -> dict:
        base = baseline or set()
        return {
            "schema": SCHEMA,
            "files": self.files,
            "suppressed": self.suppressed,
            "errors": self.errors,
            "findings": [{**f.to_dict(),
                          "baselined": f.fingerprint in base}
                         for f in self.findings],
            "fresh_count": len(self.fresh(base)),
        }


def iter_py_files(paths) -> list[str]:
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            out.extend(os.path.join(dirpath, fn)
                       for fn in sorted(filenames) if fn.endswith(".py"))
    return out


def lint_paths(paths, root: str | None = None,
               rules: list[str] | None = None) -> LintResult:
    """Run every registered rule (or the named subset) over `paths`
    (files or directories).  Finding paths are stored relative to
    `root` (default: the common parent of `paths`)."""
    files = iter_py_files(paths)
    if root is None:
        root = (os.path.commonpath([os.path.abspath(p) for p in paths])
                if paths else os.getcwd())
        if os.path.isfile(root):
            root = os.path.dirname(root)
    insts = [cls() for cls in RULE_CLASSES
             if rules is None or cls.name in rules]
    result = LintResult()
    suppr_by_path: dict[str, dict[int, set[str]]] = {}
    raw: list[Finding] = []
    for abspath in files:
        rel = os.path.relpath(os.path.abspath(abspath),
                              root).replace(os.sep, "/")
        try:
            with open(abspath, encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=abspath)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            result.errors.append(f"{rel}: {type(e).__name__}: {e}")
            continue
        _link_parents(tree)
        lines = src.splitlines()
        ctx = FileCtx(path=rel, abspath=abspath, tree=tree, lines=lines)
        suppr_by_path[rel] = suppressions(lines)
        result.files += 1
        for rule in insts:
            raw.extend(rule.visit_file(ctx))
    for rule in insts:
        raw.extend(rule.finalize())
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.col, f.rule)):
        if is_suppressed(f, suppr_by_path.get(f.path, {})):
            result.suppressed += 1
        else:
            result.findings.append(f)
    return result


# ---------------------------------------------------------------------------
# shared AST helpers for the rule modules
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """`a.b.c` for Name/Attribute chains, "" for anything else."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(node: ast.Call) -> str:
    return dotted_name(node.func)
