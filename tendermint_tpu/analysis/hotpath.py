"""tmlint JAX hot-path hygiene rules.

The PR-3 doctor can *observe* a shape-drift recompile or an implicit
host sync at runtime, but only on paths the bench happens to exercise.
These rules catch the same hazards statically, in the modules that are
on the device hot path (``ops/``, ``crypto/``, ``parallel/``):

- **jax-host-sync**: an implicit device->host synchronization —
  ``.item()``, ``float()/int()/bool()`` on a value produced by a
  ``jnp.``/``jax.`` call or a ``*_jit`` dispatch, ``np.asarray()`` of
  such a value, and explicit ``.block_until_ready()``.  Each one stalls
  the dispatch pipeline; a sync inside a per-batch loop is the
  "scalar_tail" thief the doctor reports.  Deliberate sync points live
  in ``ALLOWED_SYNC_FUNCS`` (function-scope allowlist, stable across
  line shifts) or carry an inline ``# tmlint: disable=jax-host-sync``.

- **jax-retrace**: retrace/stale-trace hazards — a jit-decorated
  function reading a *mutable* module-level global (dict/list/set
  literal: mutating it later silently does NOT retrigger tracing), and
  Python ``if``/``while`` branching on the *value* of a traced argument
  (a ConcretizationTypeError at best, a silent per-value retrace via
  implicit bool sync at worst).  Branching on ``.shape``/``.ndim``/
  ``.dtype``/``len()``/``isinstance``/``is None`` is static and fine.

- **jax-static-argnums**: ``static_argnums`` must be an int or a tuple
  of ints; a list is unhashable in older jax versions and a common typo
  (``static_argnums=[0]`` where ``(0,)`` was meant) — and a non-int
  entry means a *value* is being marked static, which recompiles per
  value.
"""

from __future__ import annotations

import ast

from tendermint_tpu.analysis.core import (FileCtx, Rule, call_name,
                                          dotted_name, register)

# path fragments (posix, relative) that put a file on the device hot path
HOT_PATH_DIRS = ("ops/", "crypto/", "parallel/")

# deliberate sync points: (path suffix, enclosing qualname).  These are
# documented synchronization barriers — e.g. the table-build
# block_until_ready in crypto/backend.py commits comb tables to device
# memory before the fsync'd cache write, and verify() must read the
# lane-mask back to return Python bools.  Function-scoped (not
# line-numbered) so edits inside the file don't rot the allowlist.
ALLOWED_SYNC_FUNCS = {
    # verify/sign API boundary: device lane-masks become Python bools
    # for the consensus/fast-sync callers — the sync IS the contract
    ("crypto/backend.py", "TpuBackend.verify_batch"),
    ("crypto/backend.py", "TpuBackend.verify_grouped"),
    ("crypto/backend.py", "TpuBackend.verify_grouped_templated"),
    ("crypto/backend.py", "TpuBackend.sign_grouped_templated"),
    # comb-table build commits tables to device memory before the
    # fsync'd on-disk cache write (backend.py "tbl.block_until_ready()")
    ("crypto/backend.py", "TpuBackend._build_tables"),
    # warm-up paths exist to absorb the compile+first-dispatch wait
    ("crypto/backend.py", "TpuBackend._warm_verify_if_cold.warm"),
    ("crypto/warmcompile.py", "_warm_one"),
}

_HOST_CASTS = {"float", "int", "bool", "complex"}

# attribute/call contexts on a traced arg that are static at trace time
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}

# jax.* calls that return host objects (device handles, ints), not
# arrays — np.array() over these is not a device->host sync
_NON_ARRAY_JAX_CALLS = {
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.default_backend", "jax.process_index",
}


def on_hot_path(path: str) -> bool:
    return any(f"/{d}" in f"/{path}" for d in HOT_PATH_DIRS)


def _is_allowed_sync(ctx: FileCtx, node: ast.AST) -> bool:
    qn = ctx.qualname_at(node)
    for suffix, func in ALLOWED_SYNC_FUNCS:
        if ctx.path.endswith(suffix) and qn == func:
            return True
    return False


# ---------------------------------------------------------------------------
# taint: which local names hold jax values?
# ---------------------------------------------------------------------------


def _expr_is_jax(node: ast.AST, tainted: set) -> bool:
    """True when the expression plausibly produces a traced/device
    value: rooted at jnp./jax., a *_jit(...) dispatch, or built from a
    tainted local."""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            name = call_name(n)
            root = name.split(".", 1)[0]
            leaf = name.rsplit(".", 1)[-1]
            if name in _NON_ARRAY_JAX_CALLS:
                continue
            if root in ("jnp", "jax") or leaf.endswith("_jit"):
                return True
        elif isinstance(n, ast.Name) and n.id in tainted:
            return True
    return False


def _function_taint(fn: ast.AST) -> set:
    """Fixpoint over simple assignments: locals assigned from jax-ish
    expressions.  Parameters are NOT tainted (a helper taking `limbs`
    may legitimately receive numpy) — only provenance visible inside
    the function counts."""
    tainted: set = set()
    for _ in range(4):                       # small fixpoint
        grew = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            else:
                continue
            value = getattr(node, "value", None)
            if value is None or not _expr_is_jax(value, tainted):
                continue
            for tgt in targets:
                els = tgt.elts if isinstance(tgt, (ast.Tuple,
                                                   ast.List)) else [tgt]
                for el in els:
                    if isinstance(el, ast.Name) and el.id not in tainted:
                        tainted.add(el.id)
                        grew = True
        if not grew:
            break
    return tainted


# ---------------------------------------------------------------------------
# jit application discovery
# ---------------------------------------------------------------------------


def _jit_applications(tree: ast.AST):
    """Yield (call_or_decorator_node, static_argnums_value_node_or_None,
    target_fn_def_or_None) for every jax.jit application in the module:
    decorators (`@jax.jit`, `@partial(jax.jit, ...)`) and direct calls
    (`f_jit = jax.jit(f, ...)`)."""
    fn_defs = {n.name: n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    def is_jit_name(name: str) -> bool:
        return name in ("jit", "jax.jit", "pjit", "jax.pjit")

    def static_kw(call: ast.Call):
        for kw in call.keywords:
            if kw.arg in ("static_argnums", "static_argnames"):
                return kw.value if kw.arg == "static_argnums" else None
        return None

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    name = call_name(dec)
                    if is_jit_name(name):
                        yield dec, static_kw(dec), node
                    elif name.rsplit(".", 1)[-1] == "partial" and \
                            dec.args and \
                            is_jit_name(dotted_name(dec.args[0])):
                        yield dec, static_kw(dec), node
                elif is_jit_name(dotted_name(dec)):
                    yield dec, None, node
        elif isinstance(node, ast.Call) and is_jit_name(call_name(node)):
            target = None
            if node.args and isinstance(node.args[0], ast.Name):
                target = fn_defs.get(node.args[0].id)
            yield node, static_kw(node), target


def _static_param_names(fn, static_node) -> set:
    """Parameter names marked static via static_argnums (constant ints
    only; anything else is handled by the static-argnums rule)."""
    idxs: set = set()
    if isinstance(static_node, ast.Constant) and \
            isinstance(static_node.value, int):
        idxs = {static_node.value}
    elif isinstance(static_node, (ast.Tuple, ast.List)):
        idxs = {el.value for el in static_node.elts
                if isinstance(el, ast.Constant)
                and isinstance(el.value, int)}
    args = fn.args.posonlyargs + fn.args.args
    return {a.arg for i, a in enumerate(args) if i in idxs}


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


@register
class HostSyncRule(Rule):
    name = "jax-host-sync"
    description = ("implicit device->host sync on the hot path "
                   "(.item(), float()/int()/bool() or np.asarray() of a "
                   "jax value, block_until_ready) outside the allowlist "
                   "of deliberate sync points")

    def visit_file(self, ctx: FileCtx):
        if not on_hot_path(ctx.path):
            return
        fns = [n for n in ast.walk(ctx.tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        taint_by_fn = {id(fn): _function_taint(fn) for fn in fns}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            # .item() / .block_until_ready() on anything
            if isinstance(node.func, ast.Attribute):
                meth = node.func.attr
                if meth == "item":
                    if not _is_allowed_sync(ctx, node):
                        yield ctx.finding(
                            self.name, node,
                            ".item() forces a device->host sync; keep "
                            "the value on device or move the read to a "
                            "deliberate sync point")
                    continue
                if meth == "block_until_ready":
                    if not _is_allowed_sync(ctx, node):
                        yield ctx.finding(
                            self.name, node,
                            "block_until_ready() outside the allowlist "
                            "of deliberate sync points (ALLOWED_SYNC_"
                            "FUNCS in analysis/hotpath.py)")
                    continue
            name = call_name(node)
            leaf = name.rsplit(".", 1)[-1]
            root = name.split(".", 1)[0]
            is_cast = name in _HOST_CASTS
            is_np_pull = (root in ("np", "numpy", "onp")
                          and leaf in ("asarray", "array"))
            if not (is_cast or is_np_pull) or not node.args:
                continue
            arg = node.args[0]
            tainted = self._taint_for(ctx, node, taint_by_fn)
            if _expr_is_jax(arg, tainted):
                if _is_allowed_sync(ctx, node):
                    continue
                what = (f"{name}() on a jax value" if is_cast
                        else f"{name}() of a jax value")
                yield ctx.finding(
                    self.name, node,
                    f"{what} forces a device->host sync on the hot path")

    @staticmethod
    def _taint_for(ctx, node, taint_by_fn) -> set:
        cur = getattr(node, "_tmlint_parent", None)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return taint_by_fn.get(id(cur), set())
            cur = getattr(cur, "_tmlint_parent", None)
        return set()


@register
class RetraceRule(Rule):
    name = "jax-retrace"
    description = ("retrace/stale-trace hazard: jit function closing "
                   "over a mutable module global, or Python if/while on "
                   "the value of a traced argument")

    def visit_file(self, ctx: FileCtx):
        if not on_hot_path(ctx.path):
            return
        mutable_globals = self._mutable_globals(ctx.tree)
        for _, static_node, fn in _jit_applications(ctx.tree):
            if fn is None:
                continue
            static = _static_param_names(fn, static_node)
            yield from self._check_globals(ctx, fn, mutable_globals)
            yield from self._check_branches(ctx, fn, static)

    @staticmethod
    def _mutable_globals(tree) -> set:
        """Module-level names bound to dict/list/set literals or
        comprehensions — the containers whose later mutation a traced
        closure will never see."""
        out = set()
        body = getattr(tree, "body", ())
        for st in body:
            if isinstance(st, ast.Assign) and isinstance(
                    st.value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                               ast.ListComp, ast.SetComp)):
                for tgt in st.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        return out

    def _check_globals(self, ctx, fn, mutable_globals):
        local = {a.arg for a in fn.args.posonlyargs + fn.args.args
                 + fn.args.kwonlyargs}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        local.add(tgt.id)
        for node in ast.walk(fn):
            if (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in mutable_globals
                    and node.id not in local):
                yield ctx.finding(
                    self.name, node,
                    f"jit-traced function reads mutable module global "
                    f"'{node.id}'; mutating it later will NOT retrace — "
                    f"pass it as an argument or make it immutable")

    def _check_branches(self, ctx, fn, static_params):
        args = fn.args.posonlyargs + fn.args.args
        traced = {a.arg for a in args} - static_params - {"self"}
        if not traced:
            return
        for node in ast.walk(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            bad = self._value_uses(node.test, traced)
            for name_node in bad:
                yield ctx.finding(
                    self.name, node,
                    f"Python {type(node).__name__.lower()} on the value "
                    f"of traced argument '{name_node.id}' "
                    f"(ConcretizationTypeError / silent host sync); "
                    f"branch on shapes, mark it static, or use "
                    f"jnp.where/lax.cond")

    @staticmethod
    def _value_uses(test, traced):
        """Name nodes of traced params whose *value* the test reads —
        shape/ndim/dtype/len/isinstance/`is None` uses are static and
        excluded."""
        static_parents: set = set()
        for n in ast.walk(test):
            if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
                static_parents.update(id(x) for x in ast.walk(n))
            elif isinstance(n, ast.Call) and call_name(n) in (
                    "len", "isinstance", "getattr", "hasattr", "type"):
                static_parents.update(id(x) for x in ast.walk(n))
            elif isinstance(n, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
                static_parents.update(id(x) for x in ast.walk(n))
        return [n for n in ast.walk(test)
                if isinstance(n, ast.Name) and n.id in traced
                and id(n) not in static_parents]


@register
class BenchScalarLoopRule(Rule):
    """The replay pipeline's host stages (bench.prep / bench.apply spans)
    overlap the device stage only while they hold the GIL briefly — a
    per-item Python loop inside one turns the stage back into the scalar
    tail the PR-12 vectorization removed (window_commit_lanes /
    apply_window).  Statement loops only: comprehensions and
    numpy/executor calls are the sanctioned idiom."""

    name = "bench-scalar-loop"
    description = ("per-item Python for/while inside a prep/apply-"
                   "categorized bench.* tracing span; vectorize the "
                   "window (window_commit_lanes, execution.apply_window) "
                   "instead")

    def visit_file(self, ctx: FileCtx):
        # deliberately NOT hot-path-dir-gated: the spans live in bench.py
        from tendermint_tpu.utils.tracing import (CAT_APPLY, CAT_PREP,
                                                  default_category)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            span_name = self._span_name(node)
            if span_name is None or not span_name.startswith("bench."):
                continue
            if default_category(span_name) not in (CAT_PREP, CAT_APPLY):
                continue
            for loop in self._stmt_loops(node.body):
                yield ctx.finding(
                    self.name, loop,
                    f"per-item {type(loop).__name__.lower()} loop inside "
                    f"the {span_name!r} span serializes a pipeline host "
                    f"stage under the GIL; assemble the window in one "
                    f"vectorized pass (window_commit_lanes / "
                    f"execution.apply_window)")

    @staticmethod
    def _span_name(node: ast.With):
        """The string-constant name of a tracing span opened by this
        `with`, or None (dynamic names can't be categorized statically)."""
        for item in node.items:
            call = item.context_expr
            if not isinstance(call, ast.Call) or not call.args:
                continue
            fn = call.func
            if not ((isinstance(fn, ast.Name) and fn.id == "span")
                    or (isinstance(fn, ast.Attribute)
                        and fn.attr == "span")):
                continue
            arg0 = call.args[0]
            if isinstance(arg0, ast.Constant) and isinstance(arg0.value,
                                                             str):
                return arg0.value
        return None

    @staticmethod
    def _stmt_loops(stmts):
        """Outermost statement-level loops under `stmts`, not descending
        into nested function/lambda definitions (a helper DEFINED inside
        the span body runs elsewhere)."""
        out, stack = [], list(stmts)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, (ast.For, ast.AsyncFor, ast.While)):
                out.append(n)
                continue
            stack.extend(ast.iter_child_nodes(n))
        return sorted(out, key=lambda n: n.lineno)


@register
class StaticArgnumsRule(Rule):
    name = "jax-static-argnums"
    description = ("static_argnums must be an int or tuple of ints "
                   "(lists/odd shapes recompile per call or fail to "
                   "hash)")

    def visit_file(self, ctx: FileCtx):
        if not on_hot_path(ctx.path):
            return
        for app, static_node, _fn in _jit_applications(ctx.tree):
            if static_node is None:
                continue
            if isinstance(static_node, ast.Constant):
                if not isinstance(static_node.value, int):
                    yield ctx.finding(
                        self.name, static_node,
                        f"static_argnums={static_node.value!r} is not an "
                        f"int or tuple of ints")
                continue
            if isinstance(static_node, ast.Tuple):
                bad = [el for el in static_node.elts
                       if isinstance(el, ast.Constant)
                       and not isinstance(el.value, int)]
                for el in bad:
                    yield ctx.finding(
                        self.name, el,
                        f"static_argnums entry {el.value!r} is not an "
                        f"int")
                continue
            yield ctx.finding(
                self.name, static_node,
                "static_argnums should be an int or a TUPLE of ints, "
                f"not a {type(static_node).__name__.lower().replace('ast.', '')} "
                "expression")
