"""tmlint: AST-based invariant checker for this codebase.

The framework carries three hand-maintained invariant families that
nothing used to enforce: lock discipline across the threaded modules
(the reference implementation leans on Go's race detector, which the
Python port lost), JAX hot-path hygiene (the runtime doctor can only
observe a shape-drift recompile or an implicit host sync on paths the
bench happens to exercise), and registration conventions (unsafe-gating
of `debug_*`/`unsafe_*` RPC routes, category-prefixed span names feeding
`utils/attribution.py`, Prometheus-valid metric names).  tmlint makes
violations fail tier-1 instead of surfacing as a 12x bench regression or
a deadlocked replay.

Run it as `python -m tendermint_tpu.cli lint` (add `--json` for machine
output); `tests/test_tmlint_repo.py` runs the same pass in tier-1.

Rule families (see each module's docstring for details):

- `locks.py`     lock-order / unlocked-write   (lock discipline)
- `hotpath.py`   jax-host-sync / jax-retrace / jax-static-argnums
- `conventions.py` route-gating / route-write-containment /
                 span-category / metric-name
- `batchplane_rule.py` batchplane-producer (verify work must ride the
                 shared device batch plane)

Suppression and grandfathering:

- inline: append ``# tmlint: disable=<rule>[,<rule>...]`` (or
  ``disable=all``) to the offending line;
- baseline: `analysis/baseline.json` holds fingerprints of grandfathered
  findings — `cli lint --update-baseline` regenerates it.  New hot-path
  modules must not be baselined (README "Static analysis").
"""

from tendermint_tpu.analysis.core import (Finding, LintResult, all_rules,
                                          baseline_path, lint_paths,
                                          load_baseline, save_baseline)

# importing the rule modules registers their rule classes
from tendermint_tpu.analysis import (batchplane_rule, conventions,  # noqa: E402,F401  (registration import)
                                     hotpath, locks)

__all__ = ["Finding", "LintResult", "all_rules", "baseline_path",
           "lint_paths", "load_baseline", "save_baseline"]
