"""tmlint lock-discipline rules.

The reference implementation leans on Go's race detector to keep its ~30
goroutine-heavy modules honest; the Python port lost that, and the two
bug classes it would have caught here are:

- **lock-order**: a cycle in the static lock-acquisition graph — class A
  acquires its lock and, while holding it, calls into something that
  acquires lock B, while another path acquires B then A.  Two threads on
  the two paths deadlock.  The graph is built per class from
  ``with self._lock:`` blocks (and ``.acquire()`` calls), following
  method calls on ``self`` and on member objects whose class is known
  (``self.pool = BlockPool(...)`` in ``__init__``), transitively.

- **unlocked-write**: an instance attribute written both inside and
  outside the owning class's ``with self._lock:`` blocks (``__init__``
  excluded — construction is single-threaded).  This is the bug class
  behind the PR-2 `/validators` accum fix: a reader snapshotting state
  under the lock can interleave with the unlocked writer.  Container
  mutations (``self.x.append(...)``) count as writes.

Single-writer designs that deliberately write without the lock should
say so with an inline ``# tmlint: disable=unlocked-write`` at the write
site — the suppression comment is the documentation.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from tendermint_tpu.analysis.core import (FileCtx, Finding, Rule,
                                          dotted_name, register)

# attribute names that look like locks even when the assignment of a
# threading ctor isn't in view (helper-constructed locks)
_LOCKNAME_RE = re.compile(r"lock|mtx|mutex|cv|cond", re.I)

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore", "new_lock", "WitnessLock"}

# method names on `self.<attr>` treated as mutations of <attr>
_MUTATORS = {"append", "extend", "add", "discard", "remove", "pop",
             "popleft", "append_left", "appendleft", "clear", "update",
             "insert", "setdefault"}

# method names too generic for unique-definer call resolution: a
# `self._data.get(k)` is a dict, not whichever scanned class happens to
# define get() — resolving it would invent lock edges out of thin air
_GENERIC_METHS = _MUTATORS | {
    "get", "items", "keys", "values", "popitem", "copy", "count",
    "index", "sort", "join", "split", "strip", "encode", "decode",
    "format", "read", "write", "close", "open", "flush", "send",
    "recv", "put", "get_nowait", "put_nowait", "start", "stop",
    "wait", "notify", "notify_all", "acquire", "release", "set",
    "is_set",
}


def _self_attr(node: ast.AST) -> str | None:
    """'x' for `self.x`, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


@dataclass
class _Site:
    path: str
    line: int
    col: int
    symbol: str


@dataclass
class _MethodInfo:
    name: str = ""
    acquires: set = field(default_factory=set)       # lock attrs
    calls: list = field(default_factory=list)  # (kind, attr, meth, locked)
    # (held_frozenset, lock_attr, site): lock acquired while holding
    nested_acquires: list = field(default_factory=list)
    # (held_frozenset, kind, attr, meth, site)
    held_calls: list = field(default_factory=list)
    writes: list = field(default_factory=list)       # (attr, locked, site)


@dataclass
class _ClassInfo:
    name: str
    path: str
    lock_attrs: set = field(default_factory=set)
    members: dict = field(default_factory=dict)      # attr -> class name
    methods: dict = field(default_factory=dict)      # name -> _MethodInfo


class _LockScanBase(Rule):
    """Shared per-class scan; subclasses report from `self._classes`."""

    def __init__(self):
        self._classes: dict[str, _ClassInfo] = {}    # "path::Class"
        self._by_name: dict[str, list[str]] = {}     # Class -> [keys]

    # -- per-file collection --------------------------------------------
    def visit_file(self, ctx: FileCtx):
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                self._scan_class(ctx, node)
        return ()

    def _scan_class(self, ctx: FileCtx, cls: ast.ClassDef) -> None:
        info = _ClassInfo(name=cls.name, path=ctx.path)
        # pass 1: lock attrs + member objects from assignments
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                if isinstance(node.value, ast.Call):
                    ctor = dotted_name(node.value.func).rsplit(".", 1)[-1]
                    if ctor in _LOCK_CTORS:
                        info.lock_attrs.add(attr)
                    elif ctor[:1].isupper():
                        info.members[attr] = ctor
        # pass 2: `with self.x:` on a lock-looking name counts as a lock
        for node in ast.walk(cls):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr and _LOCKNAME_RE.search(attr):
                        info.lock_attrs.add(attr)
        # pass 3: per-method event scan (__init__ included: its writes
        # are construction and never reported, but its CALLS classify
        # private helpers as construction-only, see UnlockedWriteRule)
        for st in cls.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mi = _MethodInfo(name=st.name)
                self._scan_block(ctx, info, mi, st.body, ())
                info.methods[st.name] = mi
        if not (info.lock_attrs or info.members):
            return
        key = f"{ctx.path}::{cls.name}"
        self._classes[key] = info
        self._by_name.setdefault(cls.name, []).append(key)

    def _scan_block(self, ctx, info, mi, stmts, held) -> None:
        held = tuple(held)
        for st in stmts:
            if isinstance(st, (ast.With, ast.AsyncWith)):
                entered = list(held)
                for item in st.items:
                    self._scan_expr(ctx, info, mi, item.context_expr,
                                    tuple(entered))
                    attr = _self_attr(item.context_expr)
                    if attr in info.lock_attrs:
                        self._note_acquire(ctx, mi, attr, tuple(entered),
                                           item.context_expr)
                        entered.append(attr)
                self._scan_block(ctx, info, mi, st.body, tuple(entered))
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: runs later (thread target / callback), so
                # locks held *here* are not held *there*
                self._scan_block(ctx, info, mi, st.body, ())
            elif isinstance(st, ast.ClassDef):
                continue
            elif isinstance(st, (ast.If, ast.While)):
                self._scan_expr(ctx, info, mi, st.test, held)
                self._scan_block(ctx, info, mi, st.body, held)
                self._scan_block(ctx, info, mi, st.orelse, held)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self._scan_expr(ctx, info, mi, st.iter, held)
                self._scan_write_target(ctx, info, mi, st.target, held)
                self._scan_block(ctx, info, mi, st.body, held)
                self._scan_block(ctx, info, mi, st.orelse, held)
            elif isinstance(st, ast.Try):
                self._scan_block(ctx, info, mi, st.body, held)
                for h in st.handlers:
                    self._scan_block(ctx, info, mi, h.body, held)
                self._scan_block(ctx, info, mi, st.orelse, held)
                self._scan_block(ctx, info, mi, st.finalbody, held)
            else:
                # leaf statement: writes, calls, acquire()/release()
                self._scan_leaf(ctx, info, mi, st, held)
                held = self._apply_acquire_release(ctx, info, mi, st,
                                                  held)

    def _apply_acquire_release(self, ctx, info, mi, st, held):
        """`self.x.acquire()` holds for the rest of the current block;
        `release()` drops it."""
        for node in ast.walk(st):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            attr = _self_attr(node.func.value)
            if attr not in info.lock_attrs:
                continue
            if node.func.attr == "acquire":
                self._note_acquire(ctx, mi, attr, held, node)
                held = held + (attr,)
            elif node.func.attr == "release" and attr in held:
                idx = len(held) - 1 - held[::-1].index(attr)
                held = held[:idx] + held[idx + 1:]
        return held

    def _note_acquire(self, ctx, mi, attr, held, node) -> None:
        mi.acquires.add(attr)
        if held and attr not in held:       # re-entrant RLock: not an edge
            mi.nested_acquires.append(
                (frozenset(held), attr, self._site(ctx, node)))

    def _scan_leaf(self, ctx, info, mi, st, held) -> None:
        locked = bool(held)
        if isinstance(st, ast.Assign):
            for tgt in st.targets:
                self._scan_write_target(ctx, info, mi, tgt, held)
            self._scan_expr(ctx, info, mi, st.value, held)
        elif isinstance(st, (ast.AugAssign, ast.AnnAssign)):
            self._scan_write_target(ctx, info, mi, st.target, held)
            if st.value is not None:
                self._scan_expr(ctx, info, mi, st.value, held)
        elif isinstance(st, ast.Delete):
            for tgt in st.targets:
                self._scan_write_target(ctx, info, mi, tgt, held)
        else:
            self._scan_expr(ctx, info, mi, st, held)
        del locked

    def _scan_write_target(self, ctx, info, mi, tgt, held) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._scan_write_target(ctx, info, mi, el, held)
            return
        node = tgt
        if isinstance(node, ast.Subscript):      # self.x[k] = v
            node = node.value
        attr = _self_attr(node)
        if attr is not None and attr not in info.lock_attrs:
            mi.writes.append((attr, bool(held), self._site(ctx, tgt)))

    def _scan_expr(self, ctx, info, mi, expr, held) -> None:
        """Collect calls (and mutator-call writes) from an expression
        tree; nested lambdas/comprehensions are included — they run
        inline."""
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            meth = node.func.attr
            recv = node.func.value
            attr = _self_attr(recv)
            if attr is not None:
                if meth in _MUTATORS and attr not in info.lock_attrs:
                    mi.writes.append((attr, bool(held),
                                      self._site(ctx, node)))
                # record every `self.<attr>.<meth>()`; the target class
                # is resolved lazily in finalize (ctor-typed members
                # first, unique definer as fallback — most members are
                # injected via __init__ params, not constructed)
                mi.calls.append(("member", attr, meth, bool(held)))
                if held:
                    mi.held_calls.append(
                        (frozenset(held), "member", attr, meth,
                         self._site(ctx, node)))
            elif isinstance(recv, ast.Name) and recv.id == "self":
                mi.calls.append(("self", "", meth, bool(held)))
                if held:
                    mi.held_calls.append(
                        (frozenset(held), "self", "", meth,
                         self._site(ctx, node)))

    def _site(self, ctx: FileCtx, node: ast.AST) -> _Site:
        return _Site(ctx.path, getattr(node, "lineno", 0),
                     getattr(node, "col_offset", 0), ctx.qualname_at(node))

    # -- shared closure machinery ---------------------------------------
    def _resolve(self, cls_name: str) -> str | None:
        keys = self._by_name.get(cls_name) or ()
        return keys[0] if len(keys) == 1 else None

    def _resolve_call(self, info: _ClassInfo, attr: str,
                      meth: str) -> str | None:
        """Target class key for `self.<attr>.<meth>()`: the member's
        constructed class when `__init__` shows one, else the single
        scanned class defining <meth> (members are usually injected as
        ctor params, so the attr's type is invisible statically)."""
        tk = self._resolve(info.members.get(attr, ""))
        if tk is not None:
            return tk
        if meth in _GENERIC_METHS:
            return None
        cands = [k for k, ci in self._classes.items()
                 if meth in ci.methods and ci.name != info.name]
        return cands[0] if len(cands) == 1 else None

    def _closure(self, key: str, meth: str, memo: dict,
                 visiting: set) -> frozenset:
        """Lock NODES ("Class.attr") this method may acquire,
        transitively through self- and member-calls."""
        mk = (key, meth)
        if mk in memo:
            return memo[mk]
        if mk in visiting:
            return frozenset()
        visiting.add(mk)
        info = self._classes.get(key)
        out: set = set()
        mi = info.methods.get(meth) if info else None
        if mi is not None:
            out.update(f"{info.name}.{a}" for a in mi.acquires)
            for kind, attr, m, _locked in mi.calls:
                if kind == "self":
                    out |= self._closure(key, m, memo, visiting)
                else:
                    tk = self._resolve_call(info, attr, m)
                    if tk is not None:
                        out |= self._closure(tk, m, memo, visiting)
        visiting.discard(mk)
        memo[mk] = frozenset(out)
        return memo[mk]


@register
class LockOrderRule(_LockScanBase):
    name = "lock-order"
    description = ("cycle in the static lock-acquisition graph "
                   "(potential deadlock between two threads taking the "
                   "locks in opposite orders)")

    def finalize(self):
        # edges: holder lock node -> acquired lock node, with one sample
        # site per edge
        edges: dict[str, dict[str, _Site]] = {}

        def add_edge(a: str, b: str, site: _Site) -> None:
            if a != b:
                edges.setdefault(a, {}).setdefault(b, site)

        memo: dict = {}
        for key, info in self._classes.items():
            for mi in info.methods.values():
                if mi.name in ("__init__", "__new__"):
                    continue        # construction is single-threaded
                for held, attr, site in mi.nested_acquires:
                    for h in held:
                        add_edge(f"{info.name}.{h}", f"{info.name}.{attr}",
                                 site)
                for held, kind, attr, meth, site in mi.held_calls:
                    if kind == "self":
                        tgt = self._closure(key, meth, memo, set())
                    else:
                        tk = self._resolve_call(info, attr, meth)
                        tgt = (self._closure(tk, meth, memo, set())
                               if tk else frozenset())
                    for h in held:
                        hn = f"{info.name}.{h}"
                        for t in tgt:
                            add_edge(hn, t, site)
        return self._report_cycles(edges)

    def _report_cycles(self, edges):
        findings, seen = [], set()
        for a in sorted(edges):
            for b in sorted(edges[a]):
                path = self._find_path(edges, b, a)
                if path is None:
                    continue
                cycle = [a] + path               # a -> b -> ... -> a
                if frozenset(cycle) in seen:
                    continue
                seen.add(frozenset(cycle))
                site = edges[a][b]
                findings.append(Finding(
                    rule=self.name, path=site.path, line=site.line,
                    col=site.col, symbol=site.symbol,
                    message=("lock-order cycle: "
                             + " -> ".join(cycle + [a])
                             + f" (acquires {b} while holding {a})")))
        return findings

    @staticmethod
    def _find_path(edges, src, dst):
        """Node path src..dst following edges, or None."""
        stack, seen = [(src, [src])], {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in sorted(edges.get(node, ())):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None


@register
class UnlockedWriteRule(_LockScanBase):
    name = "unlocked-write"
    description = ("instance attribute written both inside and outside "
                   "the owning class's lock (torn-read hazard; the "
                   "/validators accum bug class)")

    def finalize(self):
        findings = []
        for key in sorted(self._classes):
            info = self._classes[key]
            if not info.lock_attrs:
                continue
            protected, skipped = self._classify_helpers(info)
            locked_attrs: set = set()
            for mi in info.methods.values():
                if mi.name in ("__init__", "__new__") or \
                        mi.name in skipped:
                    continue
                treat_locked = mi.name in protected
                locked_attrs.update(a for a, locked, _ in mi.writes
                                    if locked or treat_locked)
            for mi in info.methods.values():
                if mi.name in ("__init__", "__new__") or \
                        mi.name in protected or mi.name in skipped:
                    continue
                for attr, locked, site in mi.writes:
                    if locked or attr not in locked_attrs:
                        continue
                    findings.append(Finding(
                        rule=self.name, path=site.path, line=site.line,
                        col=site.col, symbol=site.symbol,
                        message=(f"attribute '{attr}' of class "
                                 f"{info.name} is written here without "
                                 f"the lock that guards its other "
                                 f"writes")))
        return findings

    @staticmethod
    def _classify_helpers(info: _ClassInfo) -> tuple[set, set]:
        """Private helpers whose intra-class call sites prove their
        locking context: `protected` = every caller holds a lock (or is
        construction) — writes count as locked; `skipped` = only ever
        called during construction — writes are single-threaded and not
        reported at all (the `self._load()`-from-`__init__` pattern)."""
        callers: dict[str, list] = {}     # meth -> [(caller, locked)]
        for mi in info.methods.values():
            for kind, _attr, meth, locked in mi.calls:
                if kind == "self":
                    callers.setdefault(meth, []).append((mi.name, locked))
        protected: set = set()
        skipped: set = set()
        for meth, sites in callers.items():
            mi = info.methods.get(meth)
            if mi is None or not meth.startswith("_") or \
                    meth.startswith("__"):
                continue                  # public API: callers unknown
            if all(c in ("__init__", "__new__") for c, _ in sites):
                skipped.add(meth)
            elif all(locked or c in ("__init__", "__new__")
                     for c, locked in sites):
                protected.add(meth)
        return protected, skipped
