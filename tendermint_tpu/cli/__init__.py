"""Command-line interface.

Reference: `cmd/tendermint/commands/` — `init`, `node`, `testnet`,
`gen_validator`, `show_validator`, `replay`, `unsafe_reset_all`,
`version` (file-per-command, root at `root.go:36-52`).  argparse-based;
every command takes --home.

Run as `python -m tendermint_tpu.cli <command>`.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

from tendermint_tpu import __version__
from tendermint_tpu.config import (Config, config_file, load_config_file,
                                   save_config_file)


def _load_config(args) -> Config:
    cfg = Config()
    cfg.base.home = args.home
    # config.toml (written by init/testnet) is the base layer; explicit
    # CLI flags below override it (reference: viper file + flag binding)
    cf = config_file(os.path.expanduser(args.home))
    if os.path.exists(cf):
        cfg = load_config_file(cf, cfg)
        cfg.base.home = args.home
    if getattr(args, "proxy_app", None):
        cfg.base.proxy_app = args.proxy_app
    if getattr(args, "chain_id", None):
        cfg.base.chain_id = args.chain_id
    if getattr(args, "rpc_laddr", None):
        cfg.rpc.laddr = args.rpc_laddr
    if getattr(args, "p2p_laddr", None):
        cfg.p2p.laddr = args.p2p_laddr
    if getattr(args, "seeds", None):
        cfg.p2p.seeds = args.seeds.split(",")
    if getattr(args, "crypto_backend", None):
        cfg.base.crypto_backend = args.crypto_backend
    if getattr(args, "fast_sync", None) is not None:
        cfg.base.fast_sync = args.fast_sync
    return cfg


def cmd_init(args) -> int:
    """Initialize home dir: priv validator + solo-validator genesis
    (reference cmd/tendermint/commands/init.go)."""
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivValidator
    cfg = _load_config(args)
    root = cfg.base.root()
    os.makedirs(root, exist_ok=True)
    pv_file = cfg.base.priv_validator_file()
    pv = PrivValidator.load_or_generate(pv_file)
    gen_file = cfg.base.genesis_file()
    if not os.path.exists(gen_file):
        doc = GenesisDoc(
            chain_id=args.chain_id or "test-chain",
            validators=[GenesisValidator(pv.pub_key.bytes_, 10)])
        doc.save(gen_file)
        print(f"genesis written to {gen_file}")
    else:
        print(f"genesis already exists at {gen_file}")
    cf = config_file(root)
    if not os.path.exists(cf):
        save_config_file(cfg, cf)
        print(f"config written to {cf}")
    print(f"priv validator at {pv_file} ({pv.address.hex()})")
    return 0


def cmd_node(args) -> int:
    """Run the node (reference run_node.go)."""
    from tendermint_tpu.node.node import Node
    cfg = _load_config(args)
    node = Node(cfg)
    node.start()

    from tendermint_tpu.types import events as ev

    def on_block(block):
        print(f"committed block height={block.height} "
              f"txs={len(block.txs)} hash={block.hash().hex()[:12]}",
              flush=True)

    node.evsw.subscribe("cli", ev.NEW_BLOCK, on_block)
    rpc = node.rpc_server.addr if node.rpc_server else "disabled"
    print(f"node started: chain={node.state.chain_id} rpc={rpc}",
          flush=True)
    node.run_forever()
    return 0


def cmd_testnet(args) -> int:
    """Generate N validator home dirs sharing one genesis
    (reference testnet.go:14-50)."""
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivValidator
    n = args.n
    out = args.output
    os.makedirs(out, exist_ok=True)
    pvs = []
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        os.makedirs(home, exist_ok=True)
        pv = PrivValidator.load_or_generate(
            os.path.join(home, "priv_validator.json"))
        pvs.append(pv)
    doc = GenesisDoc(
        chain_id=args.chain_id or "testnet-chain",
        validators=[GenesisValidator(pv.pub_key.bytes_, 10) for pv in pvs])
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        doc.save(os.path.join(home, "genesis.json"))
        # per-node config file: distinct ports, peers pointed at node0
        base = args.base_port
        cfg = Config()
        cfg.base.home = home
        cfg.base.moniker = f"node{i}"
        cfg.rpc.laddr = f"tcp://0.0.0.0:{base + 1 + 2 * i}"
        cfg.p2p.laddr = f"tcp://0.0.0.0:{base + 2 * i}"
        if i > 0:
            cfg.p2p.persistent_peers = [f"127.0.0.1:{base}"]
        save_config_file(cfg, config_file(home))
    print(f"wrote {n} node homes under {out}")
    return 0


def cmd_gen_validator(args) -> int:
    from tendermint_tpu.types import PrivValidator
    pv = PrivValidator.generate()
    print(json.dumps({"address": pv.address.hex(),
                      "pub_key": pv.pub_key.bytes_.hex(),
                      "priv_key": pv.priv_key.seed.hex()}, indent=2))
    return 0


def cmd_show_validator(args) -> int:
    from tendermint_tpu.types import PrivValidator
    cfg = _load_config(args)
    pv = PrivValidator.load(cfg.base.priv_validator_file())
    print(json.dumps({"address": pv.address.hex(),
                      "pub_key": pv.pub_key.bytes_.hex()}, indent=2))
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """Wipe data + reset priv validator HRS (reference
    reset_priv_validator.go)."""
    from tendermint_tpu.types import PrivValidator
    cfg = _load_config(args)
    data = cfg.base.db_dir()
    if os.path.isdir(data):
        shutil.rmtree(data)
        print(f"removed {data}")
    pv_file = cfg.base.priv_validator_file()
    if os.path.exists(pv_file):
        pv = PrivValidator.load(pv_file)
        pv.reset()
        print(f"reset priv validator signing state at {pv_file}")
    return 0


def cmd_replay(args) -> int:
    """Replay stored blocks through a fresh app (reference replay.go)."""
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.proxy import ClientCreator
    from tendermint_tpu.state.execution import exec_commit_block
    from tendermint_tpu.utils.db import new_db
    cfg = _load_config(args)
    bs = BlockStore(new_db("sqlite",
                           os.path.join(cfg.base.db_dir(),
                                        "blockstore.db")))
    conns = ClientCreator(cfg.base.proxy_app).new_app_conns()
    print(f"replaying {bs.height} blocks into {cfg.base.proxy_app}")
    app_hash = b""
    for h in range(1, bs.height + 1):
        block = bs.load_block(h)
        app_hash = exec_commit_block(conns.consensus, block)
    print(f"done; final app hash {app_hash.hex()}")
    return 0


def cmd_replay_console(args) -> int:
    """Interactive WAL stepper (reference `consensus/replay.go` console:
    inspect every journalled consensus input one record at a time).

    Commands: <enter>/n = next record, d = dump decoded payload,
    q = quit.  Non-tty stdin steps through everything (scriptable).
    """
    import struct
    from tendermint_tpu.consensus import messages as M
    from tendermint_tpu.consensus.wal import (REC_ENDHEIGHT, REC_MESSAGE,
                                              REC_TIMEOUT, WAL)
    cfg = _load_config(args)
    wal_path = os.path.join(cfg.base.db_dir(), "cs.wal")
    recs = WAL.read_all(wal_path)
    print(f"{len(recs)} records in {wal_path}")
    interactive = sys.stdin.isatty()
    for i, (kind, payload) in enumerate(recs):
        if kind == REC_ENDHEIGHT:
            desc = f"ENDHEIGHT {struct.unpack('>Q', payload)[0]}"
        elif kind == REC_TIMEOUT:
            h, r, s = struct.unpack(">QIB", payload)
            desc = f"TIMEOUT h={h} r={r} step={s}"
        elif kind == REC_MESSAGE:
            try:
                desc = f"MESSAGE {type(M.decode_msg(payload)).__name__}"
            except Exception:
                desc = f"MESSAGE <undecodable {len(payload)}B>"
        else:
            desc = f"kind={kind} ({len(payload)}B)"
        print(f"[{i}] {desc}")
        if interactive:
            try:
                cmdline = input("(n)ext / (d)ump / (q)uit> ").strip().lower()
            except EOFError:        # Ctrl-D: exit like 'q'
                break
            if cmdline == "q":
                break
            if cmdline == "d":
                if kind == REC_MESSAGE:
                    try:
                        print("   ", M.decode_msg(payload))
                    except Exception as e:
                        print("    undecodable:", e)
                else:
                    print("   ", payload.hex())
    return 0


def cmd_version(args) -> int:
    print(__version__)
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tendermint_tpu",
                                description="TPU-native BFT replication")
    p.add_argument("--home", default=os.environ.get("TM_HOME",
                                                    "~/.tendermint_tpu"))
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("init", help="initialize home dir")
    sp.add_argument("--chain-id", default="")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("node", help="run the node")
    sp.add_argument("--proxy-app", dest="proxy_app", default="")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--rpc-laddr", dest="rpc_laddr", default="")
    sp.add_argument("--p2p-laddr", dest="p2p_laddr", default="")
    sp.add_argument("--seeds", default="")
    sp.add_argument("--crypto-backend", dest="crypto_backend", default="")
    sp.add_argument("--fast-sync", dest="fast_sync", action="store_true",
                    default=None)
    sp.add_argument("--no-fast-sync", dest="fast_sync",
                    action="store_false")
    sp.set_defaults(fn=cmd_node)

    sp = sub.add_parser("testnet", help="generate a local testnet")
    sp.add_argument("--n", type=int, default=4)
    sp.add_argument("--output", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--base-port", dest="base_port", type=int, default=26656)
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser("gen_validator", help="print a fresh key")
    sp.set_defaults(fn=cmd_gen_validator)

    sp = sub.add_parser("show_validator", help="print this node's key")
    sp.set_defaults(fn=cmd_show_validator)

    sp = sub.add_parser("unsafe_reset_all", help="wipe data dir")
    sp.set_defaults(fn=cmd_unsafe_reset_all)

    sp = sub.add_parser("replay_console",
                        help="step through the consensus WAL")
    sp.set_defaults(fn=cmd_replay_console)

    sp = sub.add_parser("replay", help="replay blocks into the app")
    sp.add_argument("--proxy-app", dest="proxy_app", default="")
    sp.set_defaults(fn=cmd_replay)

    sp = sub.add_parser("version", help="print version")
    sp.set_defaults(fn=cmd_version)

    args = p.parse_args(argv)
    return args.fn(args)
