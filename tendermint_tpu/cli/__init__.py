"""Command-line interface.

Reference: `cmd/tendermint/commands/` — `init`, `node`, `testnet`,
`gen_validator`, `show_validator`, `replay`, `unsafe_reset_all`,
`version` (file-per-command, root at `root.go:36-52`).  argparse-based;
every command takes --home.

Run as `python -m tendermint_tpu.cli <command>`.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

from tendermint_tpu import __version__
from tendermint_tpu.config import (Config, config_file, load_config_file,
                                   save_config_file)


def _load_config(args) -> Config:
    cfg = Config()
    cfg.base.home = args.home
    # config.toml (written by init/testnet) is the base layer; explicit
    # CLI flags below override it (reference: viper file + flag binding)
    cf = config_file(os.path.expanduser(args.home))
    if os.path.exists(cf):
        cfg = load_config_file(cf, cfg)
        cfg.base.home = args.home
    if getattr(args, "proxy_app", None):
        cfg.base.proxy_app = args.proxy_app
    if getattr(args, "chain_id", None):
        cfg.base.chain_id = args.chain_id
    if getattr(args, "rpc_laddr", None):
        cfg.rpc.laddr = args.rpc_laddr
    if getattr(args, "p2p_laddr", None):
        cfg.p2p.laddr = args.p2p_laddr
    if getattr(args, "seeds", None):
        cfg.p2p.seeds = args.seeds.split(",")
    if getattr(args, "crypto_backend", None):
        cfg.base.crypto_backend = args.crypto_backend
    if getattr(args, "fast_sync", None) is not None:
        cfg.base.fast_sync = args.fast_sync
    if getattr(args, "crypto_supervised", None) is not None:
        cfg.crypto.supervised = args.crypto_supervised
    if getattr(args, "crypto_breaker_threshold", None):
        cfg.crypto.breaker_threshold = args.crypto_breaker_threshold
    if getattr(args, "crypto_call_timeout", None):
        cfg.crypto.call_timeout_s = args.crypto_call_timeout
    if getattr(args, "crypto_spot_check", None):
        cfg.crypto.spot_check_every = args.crypto_spot_check
    return cfg


def cmd_init(args) -> int:
    """Initialize home dir: priv validator + solo-validator genesis
    (reference cmd/tendermint/commands/init.go)."""
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivValidator
    cfg = _load_config(args)
    root = cfg.base.root()
    os.makedirs(root, exist_ok=True)
    pv_file = cfg.base.priv_validator_file()
    pv = PrivValidator.load_or_generate(pv_file)
    gen_file = cfg.base.genesis_file()
    if not os.path.exists(gen_file):
        doc = GenesisDoc(
            chain_id=args.chain_id or "test-chain",
            validators=[GenesisValidator(pv.pub_key.bytes_, 10)])
        doc.save(gen_file)
        print(f"genesis written to {gen_file}")
    else:
        print(f"genesis already exists at {gen_file}")
    cf = config_file(root)
    if not os.path.exists(cf):
        save_config_file(cfg, cf)
        print(f"config written to {cf}")
    print(f"priv validator at {pv_file} ({pv.address.hex()})")
    if getattr(args, "warm_crypto", False):
        _warm_crypto(cfg)
    return 0


def _warm_crypto(cfg) -> int:
    """Pre-seed the persistent XLA compile cache + on-disk comb tables
    for this home's genesis validator set, so the node's FIRST boot is
    already warm (node boot also warms, but in a background thread —
    `node/node.py _maybe_precompile` — so a cold first boot verifies its
    first commits on the fallback backend; seeding at init moves the
    one-time compile wait to the operator's init step, VERDICT r4 #3).
    Harmless no-op on the python/native backends."""
    import time
    from tendermint_tpu.crypto import backend as cb
    from tendermint_tpu.types import GenesisDoc
    # warm the backend the HOME is configured to run, not whatever the
    # ambient env default selects (node boot does the same, node.py:46)
    be = cb.set_backend(cfg.base.crypto_backend)
    if not hasattr(be, "precompile_for_validators"):
        print(f"crypto backend {cfg.base.crypto_backend!r} has no device "
              "plane; nothing to warm")
        return 0
    doc = GenesisDoc.load(cfg.base.genesis_file())
    vals = doc.validator_set()
    t0 = time.time()
    print(f"warming crypto plane for {vals.size()} validators "
          f"(one-time; lands in the persistent caches)...", flush=True)
    be.precompile_for_validators(vals)
    print(f"crypto warm done in {time.time() - t0:.1f}s")
    return 0


def cmd_node(args) -> int:
    """Run the node (reference run_node.go)."""
    from tendermint_tpu.node.node import Node
    cfg = _load_config(args)
    node = Node(cfg)
    node.start()

    from tendermint_tpu.types import events as ev

    def on_block(block):
        print(f"committed block height={block.height} "
              f"txs={len(block.txs)} hash={block.hash().hex()[:12]}",
              flush=True)

    node.evsw.subscribe("cli", ev.NEW_BLOCK, on_block)
    rpc = node.rpc_server.addr if node.rpc_server else "disabled"
    print(f"node started: chain={node.state.chain_id} rpc={rpc}",
          flush=True)
    node.run_forever()
    return 0


def cmd_testnet(args) -> int:
    """Generate N validator home dirs sharing one genesis
    (reference testnet.go:14-50)."""
    from tendermint_tpu.types import GenesisDoc, GenesisValidator, PrivValidator
    n = args.n
    out = args.output
    os.makedirs(out, exist_ok=True)
    pvs = []
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        os.makedirs(home, exist_ok=True)
        pv = PrivValidator.load_or_generate(
            os.path.join(home, "priv_validator.json"))
        pvs.append(pv)
    doc = GenesisDoc(
        chain_id=args.chain_id or "testnet-chain",
        validators=[GenesisValidator(pv.pub_key.bytes_, 10) for pv in pvs])
    for i in range(n):
        home = os.path.join(out, f"node{i}")
        doc.save(os.path.join(home, "genesis.json"))
        # per-node config file: distinct ports, peers pointed at node0
        base = args.base_port
        cfg = Config()
        cfg.base.home = home
        cfg.base.moniker = f"node{i}"
        cfg.rpc.laddr = f"tcp://0.0.0.0:{base + 1 + 2 * i}"
        cfg.p2p.laddr = f"tcp://0.0.0.0:{base + 2 * i}"
        if i > 0:
            cfg.p2p.persistent_peers = [f"127.0.0.1:{base}"]
        save_config_file(cfg, config_file(home))
    print(f"wrote {n} node homes under {out}")
    return 0


def cmd_gen_validator(args) -> int:
    from tendermint_tpu.types import PrivValidator
    pv = PrivValidator.generate()
    print(json.dumps({"address": pv.address.hex(),
                      "pub_key": pv.pub_key.bytes_.hex(),
                      "priv_key": pv.priv_key.seed.hex()}, indent=2))
    return 0


def cmd_show_validator(args) -> int:
    from tendermint_tpu.types import PrivValidator
    cfg = _load_config(args)
    pv = PrivValidator.load(cfg.base.priv_validator_file())
    print(json.dumps({"address": pv.address.hex(),
                      "pub_key": pv.pub_key.bytes_.hex()}, indent=2))
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """Wipe data + reset priv validator HRS (reference
    reset_priv_validator.go)."""
    from tendermint_tpu.types import PrivValidator
    cfg = _load_config(args)
    data = cfg.base.db_dir()
    if os.path.isdir(data):
        shutil.rmtree(data)
        print(f"removed {data}")
    pv_file = cfg.base.priv_validator_file()
    if os.path.exists(pv_file):
        pv = PrivValidator.load(pv_file)
        pv.reset()
        print(f"reset priv validator signing state at {pv_file}")
    return 0


def cmd_replay(args) -> int:
    """Replay stored blocks through a fresh app (reference replay.go)."""
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.proxy import ClientCreator
    from tendermint_tpu.state.execution import exec_commit_block
    from tendermint_tpu.utils.db import new_db
    cfg = _load_config(args)
    bs = BlockStore(new_db("sqlite",
                           os.path.join(cfg.base.db_dir(),
                                        "blockstore.db")))
    conns = ClientCreator(cfg.base.proxy_app).new_app_conns()
    print(f"replaying {bs.height} blocks into {cfg.base.proxy_app}")
    app_hash = b""
    for h in range(1, bs.height + 1):
        block = bs.load_block(h)
        app_hash = exec_commit_block(conns.consensus, block)
    print(f"done; final app hash {app_hash.hex()}")
    return 0


def _describe_record(i: int, kind: int, payload: bytes) -> str:
    import struct
    from tendermint_tpu.consensus import messages as M
    from tendermint_tpu.consensus.wal import (REC_ENDHEIGHT, REC_MESSAGE,
                                              REC_TIMEOUT)
    if kind == REC_ENDHEIGHT:
        return f"[{i}] ENDHEIGHT {struct.unpack('>Q', payload)[0]}"
    if kind == REC_TIMEOUT:
        h, r, s = struct.unpack(">QIB", payload)
        return f"[{i}] TIMEOUT h={h} r={r} step={s}"
    if kind == REC_MESSAGE:
        try:
            return f"[{i}] MESSAGE {type(M.decode_msg(payload)).__name__}"
        except Exception:
            return f"[{i}] MESSAGE <undecodable {len(payload)}B>"
    return f"[{i}] kind={kind} ({len(payload)}B)"


def cmd_replay_console(args) -> int:
    """Interactive WAL playback console (reference
    `consensus/replay_file.go:76-230`): a live ConsensusState is driven
    record by record from the consensus WAL.

    Commands: next [N], back [N] (reset + re-feed, reference
    replayReset), until H (run to ENDHEIGHT H), rs [short|validators|
    proposal|proposal_block|locked_round|locked_block|votes], d (dump
    the next record), n (position), q.  Non-tty stdin feeds everything
    through (scriptable smoke-replay).
    """
    from tendermint_tpu.consensus import messages as M
    from tendermint_tpu.consensus.replay import Playback
    from tendermint_tpu.consensus.wal import REC_MESSAGE
    from tendermint_tpu.types.genesis import GenesisDoc
    cfg = _load_config(args)
    wal_path = os.path.join(cfg.base.db_dir(), "cs.wal")
    gen = GenesisDoc.load(cfg.base.genesis_file())
    pb = Playback(gen, wal_path,
                  proxy_app=cfg.base.proxy_app or "kvstore",
                  cfg=cfg.consensus)
    print(f"{len(pb.records)} records in {wal_path}")
    if not sys.stdin.isatty():
        while pb.count < len(pb.records):
            print(_describe_record(pb.count, *pb.records[pb.count]))
            pb.next(1)
        print(f"final round state: {pb.round_state('short')}")
        return 0
    while True:
        try:
            line = input(f"[{pb.count}/{len(pb.records)} "
                         f"{pb.round_state('short')}]> ").strip()
        except EOFError:
            break
        tok = line.split()
        cmd = tok[0] if tok else "next"

        def _arg_int(default=None):
            """Numeric argument or None; a typo must not crash the
            console and lose the replayed position."""
            if len(tok) < 2:
                return default
            try:
                return int(tok[1])
            except ValueError:
                print(f"{cmd} takes an integer argument")
                return None

        if cmd in ("q", "quit"):
            break
        elif cmd == "next":
            n = _arg_int(1)
            if n is None:
                continue
            for _ in range(n):
                if pb.count >= len(pb.records):
                    print("(end of WAL)")
                    break
                print(_describe_record(pb.count, *pb.records[pb.count]))
                pb.next(1)
        elif cmd == "back":
            n = _arg_int(1)
            if n is None:
                continue
            if n > pb.count:
                print(f"back must be <= current count ({pb.count})")
            else:
                pb.back(n)
                print(f"reset and re-fed {pb.count} records")
        elif cmd == "until":
            h = _arg_int()
            if h is None:
                print("until takes a height")
            else:
                pb.run_until(h)
        elif cmd == "rs":
            print(pb.round_state(tok[1] if len(tok) > 1 else "short"))
        elif cmd == "n":
            print(pb.count)
        elif cmd == "d":
            if pb.count < len(pb.records):
                kind, payload = pb.records[pb.count]
                if kind == REC_MESSAGE:
                    try:
                        print(M.decode_msg(payload))
                    except Exception as e:
                        print("undecodable:", e)
                else:
                    print(payload.hex())
        else:
            print("commands: next [N] | back [N] | until H | rs [field] "
                  "| d | n | q")
    return 0


def cmd_wal_fsck(args) -> int:
    """Check (and optionally repair) the consensus WAL.  Exit 0 when the
    log is clean, 1 when corruption was found (and left in place), 0
    after a successful --repair."""
    from tendermint_tpu.consensus.wal import WAL
    cfg = _load_config(args)
    path = args.wal or os.path.join(cfg.base.db_dir(), "cs.wal")
    if not os.path.exists(path):
        print(f"no WAL at {path}")
        return 1
    report = WAL.fsck(path, repair=args.repair)
    eh = report["end_heights"]
    print(f"{path}: {report['records']} records, "
          f"{len(eh)} committed heights"
          + (f" (last {eh[-1]})" if eh else ""))
    for off, skipped in report["bad_regions"]:
        print(f"  corrupt region at offset {off}: {skipped} bytes skipped")
    if report["tail_garbage"]:
        print(f"  torn/corrupt tail: {report['tail_garbage']} bytes")
    dirty = bool(report["bad_regions"] or report["tail_garbage"])
    if not dirty:
        print("clean")
        return 0
    if report["repaired"]:
        print("repaired: rewrote the log with only the valid records")
        return 0
    print("corrupt (replay will skip the bad regions; "
          "run with --repair to rewrite)")
    return 1


def _snapshot_store(args):
    from tendermint_tpu.statesync import SnapshotStore
    cfg = _load_config(args)
    root = args.dir or os.path.join(cfg.base.db_dir(), "snapshots")
    return cfg, SnapshotStore(root)


def _home_app(cfg):
    """The home's Application instance, for snapshot create/restore.
    Remote app specs (tcp://, grpc://) cannot serialize their state from
    here — the operator snapshots on the app side instead."""
    from tendermint_tpu.abci.app import create_app
    spec = cfg.base.proxy_app
    if spec.startswith(("tcp://", "grpc://")):
        raise SystemExit(f"cannot snapshot a remote app ({spec}); "
                         "snapshots need in-process app state")
    if spec in ("persistent_kvstore", "persistent_dummy"):
        os.environ.setdefault(
            "TM_KVSTORE_PATH",
            os.path.join(cfg.base.db_dir(), "kvstore_app.json"))
    return create_app(spec)


def cmd_snapshot_list(args) -> int:
    """List snapshots under the home (or --dir), torn ones included."""
    _cfg, store = _snapshot_store(args)
    valid, rejects = store.scan()
    if args.json:
        print(json.dumps({
            "dir": store.root_dir,
            "snapshots": [m.canonical_body() for m in valid],
            "rejected": [{"dir": d, "why": w} for d, w in rejects]},
            indent=1))
        return 0
    for m in valid:
        print(f"height {m.height}: {m.chunks} chunks "
              f"x {m.chunk_size}B, root {m.root.hex()[:16]}, "
              f"app_hash {m.app_hash.hex()[:16]}")
    for sdir, why in rejects:
        print(f"REJECTED {sdir}: {why}")
    if not valid and not rejects:
        print(f"no snapshots under {store.root_dir}")
    return 0


def cmd_snapshot_create(args) -> int:
    """Snapshot the home's committed state + app state."""
    from tendermint_tpu.state.state import get_state
    from tendermint_tpu.types.genesis import GenesisDoc
    from tendermint_tpu.utils.db import new_db
    cfg, store = _snapshot_store(args)
    gen = GenesisDoc.load(cfg.base.genesis_file())
    state_db = new_db("sqlite", os.path.join(cfg.base.db_dir(),
                                             "state.db"))
    state = get_state(state_db, gen)
    if state.last_block_height == 0:
        print("state is at height 0; nothing to snapshot",
              file=sys.stderr)
        return 1
    app = _home_app(cfg)
    if not app.supports_snapshots():
        print(f"app {cfg.base.proxy_app!r} does not support state "
              "snapshots", file=sys.stderr)
        return 1
    app_height = app.info().last_block_height
    if app_height != state.last_block_height:
        print(f"app height {app_height} != state height "
              f"{state.last_block_height}; refusing an inconsistent "
              "snapshot (is the node still running?)", file=sys.stderr)
        return 1
    m = store.create(state, app.snapshot_state())
    print(f"snapshot at height {m.height}: {m.chunks} chunks, "
          f"root {m.root.hex()[:16]} -> {store.snapshot_dir(m.height)}")
    return 0


def cmd_snapshot_verify(args) -> int:
    """Re-hash every chunk of every snapshot under a directory against
    its manifest (wal-fsck for snapshots).  Exit 0 only when every
    snapshot is intact; torn/corrupt ones are listed and exit 1."""
    from tendermint_tpu.statesync import SnapshotStore
    from tendermint_tpu.statesync.snapshot import MANIFEST_NAME
    target = os.path.expanduser(args.dir)
    if os.path.exists(os.path.join(target, MANIFEST_NAME)):
        # a single snapshot-XXXX dir: verify through its parent store
        root, name = os.path.split(os.path.abspath(target))
        store = SnapshotStore(root)
        valid = [m for m in store.list()
                 if store.snapshot_dir(m.height) == os.path.abspath(target)]
        rejects = [(d, w) for d, w in store.scan()[1]
                   if d == os.path.abspath(target)]
        if not valid and not rejects:
            rejects = [(target, "manifest invalid")]
    else:
        store = SnapshotStore(target)
        valid, rejects = store.scan()
    dirty = False
    for sdir, why in rejects:
        print(f"{sdir}: REJECTED ({why})")
        dirty = True
    for m in valid:
        rep = store.verify(m.height)
        if rep["ok"]:
            print(f"height {m.height}: {rep['chunks']} chunks clean")
            continue
        dirty = True
        if rep["missing_chunks"]:
            print(f"height {m.height}: missing chunks "
                  f"{rep['missing_chunks']}")
        if rep["bad_chunks"]:
            print(f"height {m.height}: corrupt chunks "
                  f"{rep['bad_chunks']} (hash mismatch)")
    if not valid and not rejects:
        print(f"no snapshots under {target}")
        return 1
    print("clean" if not dirty else
          "corrupt (a restoring peer would reject these chunks and "
          "blame the server)")
    return 1 if dirty else 0


def cmd_snapshot_restore(args) -> int:
    """Restore a home from a local snapshot: state db + app state +
    a block store bootstrapped at the snapshot height, so the node
    fast-syncs only `snapshot_height -> tip` on next boot.  The data
    dir must be fresh (init or unsafe_reset_all first)."""
    from tendermint_tpu.blockchain.store import BlockStore
    from tendermint_tpu.statesync import StateSyncer, StoreSource
    from tendermint_tpu.types.genesis import GenesisDoc
    from tendermint_tpu.utils.db import new_db
    cfg, store = _snapshot_store(args)
    gen = GenesisDoc.load(cfg.base.genesis_file())
    os.makedirs(cfg.base.db_dir(), exist_ok=True)
    block_store = BlockStore(new_db("sqlite",
                                    os.path.join(cfg.base.db_dir(),
                                                 "blockstore.db")))
    if block_store.height != 0:
        print(f"block store already at height {block_store.height}; "
              "restore needs a fresh data dir (unsafe_reset_all first)",
              file=sys.stderr)
        return 1
    app = _home_app(cfg)
    if not app.supports_snapshots():
        print(f"app {cfg.base.proxy_app!r} does not support state "
              "snapshots", file=sys.stderr)
        return 1
    src = StoreSource("local", store)
    if args.height:
        # --height pins the offer: only advertise that snapshot (other
        # heights are skipped, not blamed — they're not lying)
        all_manifests = src.manifests
        src.manifests = lambda: [m for m in all_manifests()
                                 if m.height == args.height]
        if not src.manifests():
            print(f"no valid snapshot at height {args.height} under "
                  f"{store.root_dir}", file=sys.stderr)
            return 1
    syncer = StateSyncer([src])
    state_db = new_db("sqlite", os.path.join(cfg.base.db_dir(),
                                             "state.db"))
    from tendermint_tpu.statesync import RestoreError
    try:
        state, manifest = syncer.restore(state_db, gen, app)
    except RestoreError as e:
        print(f"restore failed: {e}", file=sys.stderr)
        return 1
    if hasattr(app, "persist_state"):
        app.persist_state()
    block_store.bootstrap(manifest.height)
    print(f"restored height {manifest.height} "
          f"(app_hash {manifest.app_hash.hex()[:16]}); block store "
          f"bootstrapped — next boot fast-syncs from "
          f"{manifest.height + 1}")
    return 0


def _rpc_call(addr: str, method: str, params: dict, timeout: int = 30):
    """One JSON-RPC call; returns the result dict or raises SystemExit
    with a friendly message on an RPC-level error."""
    import urllib.request
    url = addr.rstrip("/")
    if not url.startswith("http"):
        url = "http://" + url
    body = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                       "params": params}).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        reply = json.loads(resp.read())
    if "error" in reply:
        raise SystemExit(f"rpc error: {reply['error'].get('message')} "
                         "(is rpc.unsafe enabled on the node?)")
    return reply["result"]


def _filter_trace(trace: dict, last: int, name: str) -> dict:
    """Apply --last/--name to a Chrome trace document: name filters by
    substring, last keeps the N most recent span/instant events (ts
    order); "M" metadata events always survive so thread names keep
    resolving in the viewer."""
    evs = trace.get("traceEvents", [])
    meta = [e for e in evs if e.get("ph") == "M"]
    spans = [e for e in evs if e.get("ph") != "M"]
    if name:
        spans = [e for e in spans if name in e.get("name", "")]
    if last and last > 0:
        spans = sorted(spans, key=lambda e: e.get("ts", 0))[-last:]
    return {**trace, "traceEvents": spans + meta}


def cmd_trace(args) -> int:
    """Fetch a running node's flight recorder over RPC (or filter a
    local dump with --in) and write it as Chrome trace-event JSON (open
    in Perfetto / chrome://tracing).  --last/--name narrow a 100k-block
    replay dump to the interesting tail without loading the full JSON.
    RPC mode requires the node to run with rpc.unsafe = true."""
    if args.infile:
        with open(args.infile) as f:
            trace = json.load(f)
        total = dropped = None
    else:
        params = {"format": "chrome"}
        if args.last:
            params["last"] = args.last
        if args.name:
            params["name"] = args.name
        result = _rpc_call(args.rpc, "debug_flight_recorder", params)
        trace = result["trace"]
        total, dropped = result["total"], result["dropped"]
    # local filtering applies in both modes (an old node may ignore the
    # RPC params; filtering again is idempotent)
    trace = _filter_trace(trace, args.last, args.name)
    spans = [e for e in trace["traceEvents"] if e.get("ph") != "M"]
    if args.format == "lines":
        for e in sorted(spans, key=lambda e: e.get("ts", 0)):
            dur = e.get("dur", 0.0) / 1e3
            cat = e.get("cat", "-")
            print(f"{e.get('ts', 0) / 1e6:.6f} {dur:10.3f}ms "
                  f"{cat:9s} {e.get('name', '')} "
                  f"{json.dumps(e.get('args', {}))}")
        return 0
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f)
    os.replace(tmp, args.out)
    msg = f"wrote {len(spans)} trace events to {args.out}"
    if total is not None:
        msg += f" (recorder total={total} dropped={dropped})"
    print(msg)
    return 0


def cmd_doctor(args) -> int:
    """Pipeline attribution report: where the wall clock of a replay
    went (compile / transfer / device-busy / scalar / idle) and which
    component is the largest thief of the throughput target.  Reads a
    dumped trace file (--trace, e.g. bench_trace.json) or a live node's
    flight recorder over unsafe RPC (--rpc)."""
    from tendermint_tpu.utils import attribution, ledger as ledger_mod
    if args.trace:
        with open(args.trace) as f:
            spans = attribution.spans_from_chrome(json.load(f))
    else:
        result = _rpc_call(args.rpc, "debug_flight_recorder",
                           {"format": "chrome"})
        spans = attribution.spans_from_chrome(result["trace"])
    regressions = None
    if args.ledger and os.path.exists(args.ledger):
        entries = ledger_mod.load(args.ledger)
        if entries:
            regressions = ledger_mod.compute_deltas(
                entries[:-1], entries[-1].get("configs") or {})
    report = attribution.doctor_report(spans, regressions=regressions)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(attribution.render_report(report))
    return 0


def cmd_timeline(args) -> int:
    """Merged consensus timeline across a rig: collect every node's
    height-lifecycle records (--rpc addr,addr,... via the unsafe
    debug_timeline route, skew-normalized on each node's wall-clock
    sample) or re-derive them from a dumped Chrome trace (--trace),
    write a per-node-track Chrome trace to --out, and print the
    consensus doctor report naming the largest thief per height
    range."""
    import time as _time
    from tendermint_tpu import telemetry
    if args.trace:
        from tendermint_tpu.utils import attribution
        with open(args.trace) as f:
            records = telemetry.records_from_spans(
                attribution.spans_from_chrome(json.load(f)))
        merged = {"records": records, "dropped": {}, "offsets": {}}
    else:
        dumps = []
        for addr in [a for a in args.rpc.split(",") if a.strip()]:
            try:
                d = _rpc_call(addr.strip(), "debug_timeline",
                              {"last": args.last} if args.last else {})
            except SystemExit:
                raise
            except Exception as e:   # a dead node degrades, not aborts
                d = {"node": addr.strip(), "records": None,
                     "error": str(e)}
            dumps.append(d)
        merged = telemetry.merge_dumps(dumps, ref_wall=_time.time())
    timeline = telemetry.build_timeline(merged["records"])
    report = telemetry.consensus_doctor(timeline, range_len=args.range)
    if args.out:
        trace = telemetry.to_chrome_trace(timeline)
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(trace, f)
        os.replace(tmp, args.out)
    if args.json:
        print(json.dumps({"timeline": timeline, "doctor": report,
                          "dropped": merged["dropped"]}, indent=1))
    else:
        if args.out:
            n = len(timeline["nodes"])
            print(f"wrote timeline trace ({n} node tracks, heights "
                  f"{timeline['height_range'][0]}.."
                  f"{timeline['height_range'][1]}) to {args.out}")
        for node, why in merged["dropped"].items():
            print(f"dropped {node}: {why}")
        print(telemetry.render_consensus_report(report))
    return 0 if not merged["dropped"] else 1


def cmd_bench_history(args) -> int:
    """Render the bench regression ledger: every recorded run's
    per-config rates with deltas vs the best PRIOR run, so a slow creep
    across runs reads as clearly as a cliff in one."""
    from tendermint_tpu.utils import ledger as ledger_mod
    entries = ledger_mod.load(args.ledger)
    print(ledger_mod.render_history(entries))
    return 1 if not entries else 0


def cmd_lint(args) -> int:
    """Run the tmlint static checks (tendermint_tpu/analysis/): lock
    discipline, JAX hot-path hygiene, RPC route gating, span/metric
    conventions.  Exit 0 when every finding is baselined or suppressed,
    1 when fresh findings exist, 2 when a lint path is missing."""
    from tendermint_tpu.analysis import (all_rules, baseline_path,
                                         lint_paths, load_baseline,
                                         save_baseline)
    if args.list_rules:
        for name, desc in all_rules():
            print(f"{name:24s} {desc}")
        return 0
    import tendermint_tpu
    pkg_dir = os.path.dirname(os.path.abspath(tendermint_tpu.__file__))
    repo_root = os.path.dirname(pkg_dir)
    if args.paths:
        paths, root = args.paths, None
    else:
        paths = [pkg_dir]
        bench = os.path.join(repo_root, "bench.py")
        if os.path.exists(bench):
            paths.append(bench)
        root = repo_root
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"lint: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    result = lint_paths(paths, root=root,
                        rules=args.rules.split(",") if args.rules
                        else None)
    bl_path = args.baseline or baseline_path()
    if args.update_baseline:
        save_baseline(result.findings, bl_path)
        print(f"baseline written: {len(result.findings)} findings "
              f"grandfathered at {bl_path}")
        return 0
    baseline = load_baseline(bl_path)
    fresh = result.fresh(baseline)
    if args.json:
        print(json.dumps(result.to_dict(baseline), indent=1))
    else:
        for f in result.findings:
            tag = "" if f.fingerprint not in baseline else " [baselined]"
            print(f.render() + tag)
        print(f"{result.files} files, {len(result.findings)} findings "
              f"({len(fresh)} fresh, {result.suppressed} suppressed)")
        for e in result.errors:
            print(f"parse error: {e}", file=sys.stderr)
    return 1 if fresh or result.errors else 0


def _print_scenario_result(result, as_json: bool) -> None:
    if as_json:
        print(json.dumps(result.to_dict(), indent=1))
        return
    verdict = "PASS" if result.ok else "FAIL"
    print(f"{verdict} {result.name} seed={result.seed} "
          f"({result.duration_s:.1f}s) "
          f"event_log_hash={result.event_log_hash[:16]}")
    for f in result.failures:
        print(f"  FAILED {f}")
    for b in result.budget_breaches:
        print(f"  OVER-BUDGET {b}")
    if result.artifact_dir:
        print(f"  artifacts: {result.artifact_dir}")


def cmd_chaos_list(args) -> int:
    """Catalogue of registered fault scenarios."""
    from tendermint_tpu.scenarios import SCENARIOS
    if args.json:
        print(json.dumps({
            name: {"description": sc.description,
                   "tier": "smoke" if sc.smoke else "stress",
                   "safety": [n for n, _ in sc.safety],
                   "liveness": [n for n, _ in sc.liveness]}
            for name, sc in sorted(SCENARIOS.items())}, indent=1))
        return 0
    for name, sc in sorted(SCENARIOS.items()):
        tier = "smoke " if sc.smoke else "stress"
        print(f"{name:24s} [{tier}] {sc.description}")
        print(f"{'':24s}  safety: "
              + ", ".join(n for n, _ in sc.safety))
        print(f"{'':24s}  liveness: "
              + ", ".join(n for n, _ in sc.liveness))
    return 0


def cmd_chaos_run(args) -> int:
    """Run one scenario; exit 0 when every invariant held and the run
    stayed inside its declared budget.  The same --seed replays the same
    injected-fault schedule bit-identically (verify with the printed
    event_log_hash).  With --seed-range A:B the scenario is swept over
    the half-open seed range instead."""
    from tendermint_tpu.scenarios import (parse_seed_range, run_scenario,
                                          run_sweep)
    backend = getattr(args, "backend", "") or None
    if getattr(args, "seed_range", ""):
        seeds = parse_seed_range(args.seed_range)
        out = run_sweep(
            [args.scenario], seeds,
            artifacts=args.artifacts or None,
            keep_artifacts=args.keep_artifacts, ledger_path=None,
            backend=backend,
            progress=(None if args.json
                      else lambda r: _print_scenario_result(r, False)))
        summary = out["summary"]
        if args.json:
            print(json.dumps(summary, indent=1))
        else:
            a = summary["configs"][args.scenario]
            print(f"sweep {args.scenario} seeds {args.seed_range}: "
                  f"{a['runs'] - a['failures']}/{a['runs']} passed, "
                  f"{a['breaches']} over budget (mean "
                  f"{a['mean_duration_s']}s, max {a['max_duration_s']}s, "
                  f"budget {a['budget_s']}s)")
        bad = summary["total_failures"] or summary["total_breaches"]
        return 1 if bad else 0
    result = run_scenario(args.scenario, seed=args.seed,
                          artifacts=args.artifacts or None,
                          keep_artifacts=args.keep_artifacts,
                          backend=backend)
    _print_scenario_result(result, args.json)
    return 0 if result.ok and not result.budget_breaches else 1


def cmd_chaos_replay(args) -> int:
    """Re-run a scenario from a dumped result.json manifest and compare
    event-log hashes: MATCH means the replayed run injected the exact
    fault schedule of the original (the seed-replay contract); DIVERGED
    means the scenario gained nondeterminism and its artifacts can no
    longer be trusted as reproductions."""
    from tendermint_tpu.scenarios import run_scenario
    with open(args.manifest) as f:
        manifest = json.load(f)
    name, seed = manifest["scenario"], manifest["seed"]
    want = manifest["event_log_hash"]
    # the backend rung is part of the hashed plan: a replay must run on
    # the SAME rung the original did or the hashes diverge by design
    result = run_scenario(name, seed=seed,
                          artifacts=args.artifacts or None,
                          keep_artifacts=args.keep_artifacts,
                          backend=manifest.get("backend") or None)
    _print_scenario_result(result, args.json)
    if result.event_log_hash == want:
        print(f"MATCH: replay reproduced event log {want[:16]}")
        return 0 if result.ok else 1
    print(f"DIVERGED: original {want[:16]} != replay "
          f"{result.event_log_hash[:16]} — scenario is nondeterministic")
    return 1


def cmd_chaos_smoke(args) -> int:
    """The fast smoke subset under a wall-clock budget: scenarios run in
    cheapest-first order and the remainder is SKIPPED (reported, never
    silently dropped) once the budget is spent.  The faults-tier CI
    entry point."""
    import time as _time
    from tendermint_tpu.scenarios import SCENARIOS, SMOKE_ORDER, run_scenario
    names = [n for n in SMOKE_ORDER if n in SCENARIOS]
    names += sorted(n for n, sc in SCENARIOS.items()
                    if sc.smoke and n not in names)
    t0 = _time.time()
    failed, skipped, results = [], [], []
    for name in names:
        spent = _time.time() - t0
        if args.budget and spent >= args.budget:
            skipped.append(name)
            continue
        result = run_scenario(name, seed=args.seed,
                              artifacts=args.artifacts or None,
                              keep_artifacts=args.keep_artifacts,
                              backend=getattr(args, "backend", "") or None)
        results.append(result)
        _print_scenario_result(result, args.json)
        if not result.ok:
            failed.append(name)
    for name in skipped:
        print(f"SKIP {name} (budget {args.budget:.0f}s spent)")
    print(f"chaos smoke: {len(results) - len(failed)}/{len(results)} "
          f"passed, {len(skipped)} skipped "
          f"in {_time.time() - t0:.1f}s")
    return 1 if failed else 0


def cmd_chaos_soak(args) -> int:
    """Nightly seed-sweep soak: sweep a catalogue tier across a seed
    range with per-scenario declared budgets and a global wall cap.
    Never silent — scenarios that don't fit the global budget are
    reported as SKIPPED, every failed or over-budget run prints its
    triage bundle path, and per-scenario rates land in the chaos ledger
    so a fault-path latency regression bisects like a bench regression.
    Exits nonzero on any invariant failure or budget breach."""
    import time as _time
    from tendermint_tpu.scenarios import (SCENARIOS, SMOKE_ORDER,
                                          parse_seed_range, run_sweep)
    from tendermint_tpu.scenarios.engine import CHAOS_LEDGER_SCHEMA
    from tendermint_tpu.utils import ledger as ledgermod
    seeds = parse_seed_range(args.seed_range)
    smoke = [n for n in SMOKE_ORDER if n in SCENARIOS]
    smoke += sorted(n for n, sc in SCENARIOS.items()
                    if sc.smoke and n not in smoke)
    stress = sorted(n for n, sc in SCENARIOS.items() if not sc.smoke)
    names = {"smoke": smoke, "stress": stress,
             "all": smoke + stress}[args.tier]
    if args.scenarios:
        want = [s.strip() for s in args.scenarios.split(",") if s.strip()]
        unknown = [w for w in want if w not in SCENARIOS]
        if unknown:
            print(f"unknown scenarios: {', '.join(unknown)} "
                  f"(see `chaos list`)", file=sys.stderr)
            return 2
        names = want                       # explicit list overrides tier
    t0 = _time.time()
    skipped: list[str] = []
    all_results: list = []
    configs: dict = {}
    progress = (None if args.json
                else lambda r: _print_scenario_result(r, False))
    for name in names:
        if args.budget and _time.time() - t0 >= args.budget:
            skipped.append(name)
            continue
        out = run_sweep([name], seeds, artifacts=args.artifacts or None,
                        keep_artifacts=args.keep_artifacts,
                        ledger_path=None, progress=progress,
                        backend=getattr(args, "backend", "") or None)
        configs.update(out["summary"]["configs"])
        all_results.extend(out["results"])
    failures = [r for r in all_results if not r.ok]
    breaches = [r for r in all_results if r.budget_breaches]
    deltas: dict = {}
    if args.budget_ledger:
        prior = [e for e in ledgermod.load(args.budget_ledger)
                 if e.get("schema") == CHAOS_LEDGER_SCHEMA]
        deltas = ledgermod.compute_deltas(prior, configs)
        ledgermod.append_entry(args.budget_ledger, {
            "schema": CHAOS_LEDGER_SCHEMA, "soak": True,
            "tier": args.tier, "seed_range": args.seed_range,
            "n_seeds": len(seeds), "configs": configs,
            "skipped": skipped,
            "timestamp": _time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        _time.gmtime())})
    if args.json:
        print(json.dumps({
            "tier": args.tier, "seed_range": args.seed_range,
            "configs": configs, "skipped": skipped, "deltas": deltas,
            "runs": len(all_results), "failures": len(failures),
            "breaches": len(breaches),
            "triage": sorted({r.artifact_dir for r in failures + breaches
                              if r.artifact_dir}),
            "duration_s": round(_time.time() - t0, 1)}, indent=1))
        return 1 if failures or breaches else 0
    for name in skipped:
        print(f"SKIP {name} x{len(seeds)} seeds "
              f"(global budget {args.budget:.0f}s spent)")
    for d in sorted({r.artifact_dir for r in failures + breaches
                     if r.artifact_dir}):
        print(f"triage: {d}")
    regressions = sorted(n for n, row in deltas.items()
                         if row.get("regression"))
    if regressions:
        print(f"rate regressions vs best prior: {', '.join(regressions)}")
    print(f"chaos soak [{args.tier}] seeds {args.seed_range}: "
          f"{len(all_results) - len(failures)}/{len(all_results)} passed, "
          f"{len(breaches)} over budget, {len(skipped)} scenarios "
          f"skipped in {_time.time() - t0:.1f}s"
          + (f" (ledger: {args.budget_ledger})"
             if args.budget_ledger else ""))
    return 1 if failures or breaches else 0


def cmd_chaos_nightly(args) -> int:
    """The nightly soak gate: sweep the FULL catalogue (smoke tier in
    cheapest-first order, then every stress rig) across a seed range,
    with per-seed metric-budget verdicts ledgered to the chaos ledger
    and a durable triage bundle for every failed or over-budget run.
    This is `chaos soak --tier all` hardened into a gate: per-run
    ledger entries (schema tpu-bft-chaos-run/1) land for every seed so
    a budget regression bisects to the exact scenario+seed, scenarios
    that miss the global wall cap are reported as SKIPPED (a skip is
    visible in the summary and the ledger, never silent), and the exit
    code is nonzero on any invariant failure or metric/wall budget
    breach."""
    import time as _time
    from tendermint_tpu.scenarios import (SCENARIOS, SMOKE_ORDER,
                                          parse_seed_range, run_sweep)
    from tendermint_tpu.scenarios.engine import CHAOS_LEDGER_SCHEMA
    from tendermint_tpu.utils import ledger as ledgermod
    seeds = parse_seed_range(args.seed_range)
    names = [n for n in SMOKE_ORDER if n in SCENARIOS]
    names += sorted(n for n, sc in SCENARIOS.items()
                    if sc.smoke and n not in names)
    names += sorted(n for n, sc in SCENARIOS.items() if not sc.smoke)
    if args.scenarios:
        want = [s.strip() for s in args.scenarios.split(",") if s.strip()]
        unknown = [w for w in want if w not in SCENARIOS]
        if unknown:
            print(f"unknown scenarios: {', '.join(unknown)} "
                  f"(see `chaos list`)", file=sys.stderr)
            return 2
        names = want                       # explicit list overrides
    backend = getattr(args, "backend", "") or None
    t0 = _time.time()
    skipped: list[str] = []
    all_results: list = []
    configs: dict = {}
    progress = (None if args.json
                else lambda r: _print_scenario_result(r, False))
    for name in names:
        if args.budget and _time.time() - t0 >= args.budget:
            skipped.append(name)
            continue
        # ledger_path here (unlike soak) so every seed's run lands as
        # its own tpu-bft-chaos-run/1 entry carrying the per-metric
        # budget verdicts — the nightly's bisectable record
        out = run_sweep([name], seeds, artifacts=args.artifacts or None,
                        keep_artifacts=args.keep_artifacts,
                        ledger_path=args.budget_ledger or None,
                        progress=progress, backend=backend)
        configs.update(out["summary"]["configs"])
        all_results.extend(out["results"])
    failures = [r for r in all_results if not r.ok]
    breaches = [r for r in all_results if r.budget_breaches]
    triage = sorted({r.artifact_dir for r in failures + breaches
                     if r.artifact_dir})
    deltas: dict = {}
    if args.budget_ledger:
        prior = [e for e in ledgermod.load(args.budget_ledger)
                 if e.get("schema") == CHAOS_LEDGER_SCHEMA]
        deltas = ledgermod.compute_deltas(prior, configs)
        ledgermod.append_entry(args.budget_ledger, {
            "schema": CHAOS_LEDGER_SCHEMA, "nightly": True,
            "seed_range": args.seed_range, "n_seeds": len(seeds),
            "configs": configs, "skipped": skipped,
            "backend": backend or "",
            "timestamp": _time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        _time.gmtime())})
    if args.json:
        print(json.dumps({
            "seed_range": args.seed_range, "configs": configs,
            "skipped": skipped, "deltas": deltas,
            "runs": len(all_results), "failures": len(failures),
            "breaches": len(breaches), "triage": triage,
            "duration_s": round(_time.time() - t0, 1)}, indent=1))
        return 1 if failures or breaches else 0
    for name in skipped:
        print(f"SKIP {name} x{len(seeds)} seeds "
              f"(global budget {args.budget:.0f}s spent)")
    for d in triage:
        print(f"triage: {d}")
    regressions = sorted(n for n, row in deltas.items()
                         if row.get("regression"))
    if regressions:
        print(f"rate regressions vs best prior: {', '.join(regressions)}")
    print(f"chaos nightly seeds {args.seed_range}: "
          f"{len(all_results) - len(failures)}/{len(all_results)} passed, "
          f"{len(breaches)} over budget, {len(skipped)} scenarios "
          f"skipped in {_time.time() - t0:.1f}s"
          + (f" (ledger: {args.budget_ledger})"
             if args.budget_ledger else ""))
    return 1 if failures or breaches else 0


def cmd_version(args) -> int:
    print(__version__)
    return 0


def cmd_probe_upnp(args) -> int:
    """Test UPnP functionality (reference
    `cmd/tendermint/commands/probe_upnp.go:1-35`)."""
    import json as _json
    from tendermint_tpu.p2p import upnp
    try:
        caps = upnp.probe(int_port=args.int_port, ext_port=args.ext_port)
    except upnp.UPnPError as e:
        print(f"Probe failed: {e}")
        return 1
    print("Probe success!")
    print(_json.dumps(caps))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tendermint_tpu",
                                description="TPU-native BFT replication")
    p.add_argument("--home", default=os.environ.get("TM_HOME",
                                                    "~/.tendermint_tpu"))
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("init", help="initialize home dir")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--warm-crypto", dest="warm_crypto",
                    action="store_true",
                    help="pre-seed the XLA compile cache + comb tables "
                         "for the genesis validator set (one-time; makes "
                         "the first node boot verify-warm)")
    sp.set_defaults(fn=cmd_init)

    sp = sub.add_parser("node", help="run the node")
    sp.add_argument("--proxy-app", dest="proxy_app", default="")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--rpc-laddr", dest="rpc_laddr", default="")
    sp.add_argument("--p2p-laddr", dest="p2p_laddr", default="")
    sp.add_argument("--seeds", default="")
    sp.add_argument("--crypto-backend", dest="crypto_backend", default="")
    sp.add_argument("--fast-sync", dest="fast_sync", action="store_true",
                    default=None)
    sp.add_argument("--no-fast-sync", dest="fast_sync",
                    action="store_false")
    sp.add_argument("--crypto-supervised", dest="crypto_supervised",
                    action="store_true", default=None,
                    help="wrap the crypto backend in the fault-tolerant "
                         "ladder (timeouts, retry, circuit breaker; see "
                         "README 'Failure semantics')")
    sp.add_argument("--no-crypto-supervised", dest="crypto_supervised",
                    action="store_false")
    sp.add_argument("--crypto-breaker-threshold", type=int, default=0,
                    dest="crypto_breaker_threshold",
                    help="consecutive device faults before the breaker "
                         "trips to the next rung")
    sp.add_argument("--crypto-call-timeout", type=float, default=0.0,
                    dest="crypto_call_timeout",
                    help="per-call device timeout in seconds")
    sp.add_argument("--crypto-spot-check", type=int, default=0,
                    dest="crypto_spot_check",
                    help="re-verify one lane of every Nth device batch "
                         "on the reference backend (0 = off)")
    sp.set_defaults(fn=cmd_node)

    sp = sub.add_parser("testnet", help="generate a local testnet")
    sp.add_argument("--n", type=int, default=4)
    sp.add_argument("--output", default="./mytestnet")
    sp.add_argument("--chain-id", default="")
    sp.add_argument("--base-port", dest="base_port", type=int, default=26656)
    sp.set_defaults(fn=cmd_testnet)

    sp = sub.add_parser("gen_validator", help="print a fresh key")
    sp.set_defaults(fn=cmd_gen_validator)

    sp = sub.add_parser("show_validator", help="print this node's key")
    sp.set_defaults(fn=cmd_show_validator)

    sp = sub.add_parser("unsafe_reset_all", help="wipe data dir")
    sp.set_defaults(fn=cmd_unsafe_reset_all)

    sp = sub.add_parser("replay_console",
                        help="step through the consensus WAL")
    sp.set_defaults(fn=cmd_replay_console)

    sp = sub.add_parser("replay", help="replay blocks into the app")
    sp.add_argument("--proxy-app", dest="proxy_app", default="")
    sp.set_defaults(fn=cmd_replay)

    sp = sub.add_parser("wal-fsck", help="check/repair the consensus WAL")
    sp.add_argument("--wal", default="",
                    help="explicit WAL path (default: <data dir>/cs.wal)")
    sp.add_argument("--repair", action="store_true",
                    help="rewrite the log keeping only valid records")
    sp.set_defaults(fn=cmd_wal_fsck)

    sp = sub.add_parser("snapshot",
                        help="state snapshots: create, verify, restore "
                             "(crashed nodes rejoin from a snapshot + a "
                             "short fast-sync tail instead of a full "
                             "replay)")
    snap_sub = sp.add_subparsers(dest="snapshot_command", required=True)

    ssp = snap_sub.add_parser("list", help="list snapshots (torn ones "
                                           "flagged)")
    ssp.add_argument("--dir", default="",
                     help="snapshot root (default: <data dir>/snapshots)")
    ssp.add_argument("--json", action="store_true")
    ssp.set_defaults(fn=cmd_snapshot_list)

    ssp = snap_sub.add_parser("create",
                              help="snapshot the home's committed state")
    ssp.add_argument("--dir", default="",
                     help="snapshot root (default: <data dir>/snapshots)")
    ssp.set_defaults(fn=cmd_snapshot_create)

    ssp = snap_sub.add_parser(
        "verify", help="re-hash every chunk against its manifest "
                       "(wal-fsck for snapshots); exit 1 on any mismatch")
    ssp.add_argument("dir", help="snapshot root or a single "
                                 "snapshot-<height> directory")
    ssp.set_defaults(fn=cmd_snapshot_verify)

    ssp = snap_sub.add_parser(
        "restore", help="restore a FRESH data dir from a snapshot; the "
                        "next boot fast-syncs only the tail")
    ssp.add_argument("--dir", default="",
                     help="snapshot root (default: <data dir>/snapshots)")
    ssp.add_argument("--height", type=int, default=0,
                     help="restore this height (default: best available)")
    ssp.set_defaults(fn=cmd_snapshot_restore)

    sp = sub.add_parser("trace",
                        help="dump a node's flight recorder as Chrome "
                             "trace JSON")
    sp.add_argument("--rpc", default="http://127.0.0.1:26657",
                    help="node RPC address")
    sp.add_argument("--out", default="flight_trace.json",
                    help="output Chrome trace-event JSON path")
    sp.add_argument("--in", dest="infile", default="",
                    help="filter a local trace dump instead of RPC")
    sp.add_argument("--last", type=int, default=0,
                    help="keep only the N most recent spans")
    sp.add_argument("--name", default="",
                    help="keep only spans whose name contains SUBSTR")
    sp.add_argument("--format", choices=("chrome", "lines"),
                    default="chrome",
                    help="chrome: write JSON to --out; lines: print "
                         "one span per line to stdout")
    sp.set_defaults(fn=cmd_trace)

    sp = sub.add_parser("doctor",
                        help="pipeline attribution report: where the "
                             "wall clock went, largest thief of the "
                             "throughput target")
    sp.add_argument("--trace", default="",
                    help="read spans from a Chrome trace dump "
                         "(e.g. bench_trace.json) instead of RPC")
    sp.add_argument("--rpc", default="http://127.0.0.1:26657",
                    help="node RPC address (used when --trace unset)")
    sp.add_argument("--ledger", default="BENCH_LEDGER.jsonl",
                    help="bench ledger to fold regression flags from "
                         "('' to skip)")
    sp.add_argument("--json", action="store_true",
                    help="print the machine-readable report instead of "
                         "the human summary")
    sp.set_defaults(fn=cmd_doctor)

    sp = sub.add_parser("timeline",
                        help="merged consensus timeline: one Chrome "
                             "track per node + consensus doctor report")
    sp.add_argument("--rpc", default="http://127.0.0.1:26657",
                    help="comma-separated node RPC addresses "
                         "(unsafe debug_timeline route)")
    sp.add_argument("--trace", default="",
                    help="re-derive the timeline from a Chrome trace "
                         "dump instead of RPC")
    sp.add_argument("--out", default="timeline_trace.json",
                    help="output Chrome trace path ('' to skip)")
    sp.add_argument("--last", type=int, default=0,
                    help="fetch only the N most recent heights per node")
    sp.add_argument("--range", type=int, default=10,
                    help="doctor height-range chunk length")
    sp.add_argument("--json", action="store_true",
                    help="print machine-readable timeline + doctor "
                         "report")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("bench-history",
                        help="render the bench regression ledger with "
                             "per-config deltas vs best prior run")
    sp.add_argument("--ledger", default="BENCH_LEDGER.jsonl",
                    help="ledger JSONL path (bench.py --ledger)")
    sp.set_defaults(fn=cmd_bench_history)

    sp = sub.add_parser("lint",
                        help="run the tmlint static invariant checks "
                             "(lock discipline, JAX hot-path hygiene, "
                             "route gating, span/metric conventions)")
    sp.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the installed "
                         "tendermint_tpu package + bench.py)")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable findings document")
    sp.add_argument("--rules", default="",
                    help="comma-separated rule subset to run")
    sp.add_argument("--baseline", default="",
                    help="baseline file (default: "
                         "tendermint_tpu/analysis/baseline.json)")
    sp.add_argument("--update-baseline", action="store_true",
                    dest="update_baseline",
                    help="grandfather the current findings and exit 0")
    sp.add_argument("--list-rules", action="store_true",
                    dest="list_rules", help="print the rule catalog")
    sp.set_defaults(fn=cmd_lint)

    sp = sub.add_parser("chaos",
                        help="deterministic fault-scenario harness "
                             "(byzantine votes, partitions, crash "
                             "storms, device faults)")
    chaos_sub = sp.add_subparsers(dest="chaos_command", required=True)

    def _chaos_common(csp, scenario_arg: bool):
        from tendermint_tpu.scenarios.engine import (DEFAULT_SEED,
                                                     KNOWN_BACKENDS)
        if scenario_arg:
            csp.add_argument("--scenario", required=True,
                             help="scenario name (see `chaos list`)")
        csp.add_argument("--seed", type=int, default=DEFAULT_SEED,
                         help="scenario seed; the same seed replays the "
                              "same fault schedule (default: %(default)s)")
        csp.add_argument("--backend", choices=list(KNOWN_BACKENDS),
                         default="",
                         help="crypto backend rung for the run "
                              "(overrides TM_SCENARIO_BACKEND and the "
                              "scenario's declared default)")
        csp.add_argument("--artifacts", default="",
                         help="artifact root (default: "
                              "$TM_SCENARIO_ARTIFACTS or "
                              "./chaos_artifacts)")
        csp.add_argument("--keep-artifacts", dest="keep_artifacts",
                         action="store_true",
                         help="dump trace/metrics/events/result even on "
                              "a passing run")
        csp.add_argument("--json", action="store_true",
                         help="machine-readable result")

    csp = chaos_sub.add_parser("list", help="catalogue of scenarios")
    csp.add_argument("--json", action="store_true")
    csp.set_defaults(fn=cmd_chaos_list)

    csp = chaos_sub.add_parser("run", help="run one scenario")
    _chaos_common(csp, scenario_arg=True)
    csp.add_argument("--seed-range", dest="seed_range", default="",
                     help="sweep a half-open seed range A:B (e.g. 0:25) "
                          "instead of the single --seed")
    csp.set_defaults(fn=cmd_chaos_run)

    csp = chaos_sub.add_parser(
        "replay", help="re-run from a dumped result.json and check the "
                       "event-log hash matches")
    csp.add_argument("--manifest", required=True,
                     help="path to a result.json from a prior run")
    csp.add_argument("--artifacts", default="")
    csp.add_argument("--keep-artifacts", dest="keep_artifacts",
                     action="store_true")
    csp.add_argument("--json", action="store_true")
    csp.set_defaults(fn=cmd_chaos_replay)

    csp = chaos_sub.add_parser(
        "smoke", help="run the smoke subset under a time budget")
    _chaos_common(csp, scenario_arg=False)
    csp.add_argument("--budget", type=float, default=300.0,
                     help="wall-clock budget in seconds; scenarios that "
                          "don't fit are reported as skipped "
                          "(default: %(default)s)")
    csp.set_defaults(fn=cmd_chaos_smoke)

    from tendermint_tpu.scenarios.engine import (DEFAULT_CHAOS_LEDGER,
                                                 KNOWN_BACKENDS
                                                 as _KNOWN_BACKENDS)
    csp = chaos_sub.add_parser(
        "soak", help="nightly seed-sweep soak across a catalogue tier "
                     "with budget enforcement and a chaos ledger")
    csp.add_argument("--seed-range", dest="seed_range", default="0:3",
                     help="half-open seed range A:B to sweep "
                          "(default: %(default)s)")
    csp.add_argument("--tier", choices=["smoke", "stress", "all"],
                     default="smoke",
                     help="catalogue tier to sweep (default: %(default)s)")
    csp.add_argument("--scenarios", default="",
                     help="comma-separated scenario names; overrides "
                          "--tier when given")
    csp.add_argument("--budget", type=float, default=0.0,
                     help="global wall-clock cap in seconds; scenarios "
                          "that don't fit are reported as SKIPPED, never "
                          "silently dropped (0 = uncapped)")
    csp.add_argument("--budget-ledger", dest="budget_ledger",
                     default=DEFAULT_CHAOS_LEDGER,
                     help="chaos ledger path for per-scenario rates and "
                          "regression deltas; empty to disable "
                          "(default: %(default)s)")
    csp.add_argument("--backend", choices=list(_KNOWN_BACKENDS),
                     default="",
                     help="crypto backend rung for every run (overrides "
                          "TM_SCENARIO_BACKEND and scenario defaults)")
    csp.add_argument("--artifacts", default="")
    csp.add_argument("--keep-artifacts", dest="keep_artifacts",
                     action="store_true")
    csp.add_argument("--json", action="store_true")
    csp.set_defaults(fn=cmd_chaos_soak)

    csp = chaos_sub.add_parser(
        "nightly", help="the nightly soak gate: full-catalogue seed "
                        "sweep with per-seed metric-budget verdicts "
                        "ledgered and durable triage bundles on breach")
    csp.add_argument("--seed-range", dest="seed_range", default="0:5",
                     help="half-open seed range A:B to sweep "
                          "(default: %(default)s)")
    csp.add_argument("--scenarios", default="",
                     help="comma-separated scenario names; overrides "
                          "the full catalogue when given")
    csp.add_argument("--budget", type=float, default=0.0,
                     help="global wall-clock cap in seconds; scenarios "
                          "that don't fit are reported as SKIPPED, never "
                          "silently dropped (0 = uncapped)")
    csp.add_argument("--budget-ledger", dest="budget_ledger",
                     default=DEFAULT_CHAOS_LEDGER,
                     help="chaos ledger path; every seed's run lands as "
                          "its own entry with metric-budget verdicts, "
                          "plus one aggregate row (default: %(default)s)")
    csp.add_argument("--backend", choices=list(_KNOWN_BACKENDS),
                     default="",
                     help="crypto backend rung for every run (overrides "
                          "TM_SCENARIO_BACKEND and scenario defaults)")
    csp.add_argument("--artifacts", default="")
    csp.add_argument("--keep-artifacts", dest="keep_artifacts",
                     action="store_true")
    csp.add_argument("--json", action="store_true")
    csp.set_defaults(fn=cmd_chaos_nightly)

    sp = sub.add_parser("version", help="print version")
    sp.set_defaults(fn=cmd_version)

    sp = sub.add_parser("probe_upnp", help="test UPnP functionality")
    sp.add_argument("--int-port", dest="int_port", type=int, default=20000)
    sp.add_argument("--ext-port", dest="ext_port", type=int, default=20000)
    sp.set_defaults(fn=cmd_probe_upnp)

    args = p.parse_args(argv)
    return args.fn(args)
