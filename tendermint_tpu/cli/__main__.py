import sys

from tendermint_tpu.cli import main

sys.exit(main())
