"""The unified device batch plane: ONE verify scheduler for every
workload.

Before this module, each producer of signature-verify work — consensus
vote ingest, fast-sync window verify, light-client chain verifies, and
mempool CheckTx — micro-batched onto the device independently, so
concurrent workloads fought for the chip and padded separate half-full
batches.  This is the Blockchain Machine architecture (arXiv:2104.06968)
applied to the jax_graft crypto plane: a single submission queue
coalesces lanes from ALL producers into the fixed pre-warmed chunk
shapes the backend already buckets to, and one worker drains it onto the
supervised crypto ladder.

Scheduling contract:

* **Priority classes.**  Every submission carries a class —
  ``consensus`` > ``fastsync`` > ``mempool`` > ``light`` — and when more
  than one coalesced batch is ready to ship, the highest class ships
  first: consensus votes preempt light-client queries and CheckTx.
* **Deadline-aware flushing.**  A batch ships when it is FULL (its lane
  count reaches the chunk target) or when its oldest submission's
  deadline arrives — latency-sensitive votes never wait on a
  slow-filling batch, and bulk fast-sync lanes wait just long enough to
  coalesce.  Each class has a default max queue wait
  (`TM_BATCHPLANE_WAIT_<CLASS>` overrides, seconds).
* **Per-producer fairness.**  When a flush must truncate (more lanes
  queued than the per-flush cap), lanes are taken round-robin across
  producers, so a flooding producer cannot starve the others out of a
  batch; leftovers stay queued at their original deadlines.
* **Fault isolation.**  The flush executes through the module-level
  `crypto.backend` helpers, i.e. UNDER the SupervisedBackend ladder —
  DeviceFault blame, `TM_CHAOS_CRYPTO` chaos injection, and rung
  demotion all apply unchanged.  A DeviceFault mid-batch fails ONLY the
  submissions in that flush; queued work is untouched and later flushes
  proceed.

Merging rules follow the backend's entry points: plain grouped lanes
merge per validator-set key, templated lanes merge per set key with
template-index rebasing (the `merge_commit_lanes` layout), raw
per-lane ed25519 lanes merge across ALL producers (the mempool CheckTx
lane rides next to anything), and secp256k1 lanes coalesce into one
host-side pass (`crypto/secp256k1.py` is OpenSSL-backed; there is no
device kernel for it yet, but the queue discipline and fairness are
identical so a future device lane slots in unchanged).

`TM_BATCHPLANE=0` bypasses the queue entirely (each submission executes
inline on the caller's thread through the same backend helpers) — the
escape hatch for single-workload benches that want zero added latency.

Everything is observable: batch-occupancy and queue-depth histograms,
per-class wait-time histograms, flush-reason and per-producer lane
counters, and a mixed-batch counter proving cross-producer coalescing
(see README "Unified batch plane" for the metric table).
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

from tendermint_tpu.utils.log import get_logger
from tendermint_tpu.utils.metrics import REGISTRY

log = get_logger("batchplane")

# -- priority classes --------------------------------------------------------

CLASS_CONSENSUS = "consensus"
CLASS_FASTSYNC = "fastsync"
CLASS_MEMPOOL = "mempool"
CLASS_LIGHT = "light"

# lower number = higher priority (consensus preempts everything)
CLASS_PRIORITY = {CLASS_CONSENSUS: 0, CLASS_FASTSYNC: 1,
                  CLASS_MEMPOOL: 2, CLASS_LIGHT: 3}

# default max queue wait (seconds) before a submission's batch must ship
# even half-empty: votes are on the live-round critical path, fast-sync
# windows arrive in bulk and can afford to coalesce longer
_DEFAULT_WAIT = {CLASS_CONSENSUS: 0.002, CLASS_FASTSYNC: 0.02,
                 CLASS_MEMPOOL: 0.010, CLASS_LIGHT: 0.025}


def class_max_wait(klass: str) -> float:
    env = os.environ.get(f"TM_BATCHPLANE_WAIT_{klass.upper()}")
    if env:
        try:
            return max(float(env), 0.0)
        except ValueError:
            pass
    return _DEFAULT_WAIT.get(klass, 0.02)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def enabled() -> bool:
    return os.environ.get("TM_BATCHPLANE", "1") not in ("0", "false", "no")


# -- submissions -------------------------------------------------------------


class Submission:
    """One producer's slice of a future device batch.  `wait()` blocks
    until the worker flushed the batch and returns this slice's bool
    lanes — or re-raises the flush's error (DeviceFault et al) so the
    producer's existing blame handling fires unchanged."""

    __slots__ = ("kind", "key", "producer", "klass", "deadline", "enq_t",
                 "arrays", "n", "_event", "_result", "_error")

    def __init__(self, kind, key, producer, klass, deadline, arrays, n):
        self.kind = kind
        self.key = key
        self.producer = producer
        self.klass = klass
        self.deadline = deadline
        self.enq_t = time.perf_counter()
        self.arrays = arrays
        self.n = n
        self._event = threading.Event()
        self._result = None
        self._error = None

    def _resolve(self, result) -> None:
        self._result = result
        self._event.set()

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()

    def wait(self) -> np.ndarray:
        self._event.wait()
        if self._error is not None:
            raise self._error
        return self._result


class _PendingBatch:
    """Submissions sharing one merge key, in arrival order."""

    __slots__ = ("key", "subs", "lanes")

    def __init__(self, key):
        self.key = key
        self.subs: list[Submission] = []
        self.lanes = 0

    def add(self, sub: Submission) -> None:
        self.subs.append(sub)
        self.lanes += sub.n

    @property
    def priority(self) -> int:
        return min(CLASS_PRIORITY.get(s.klass, 9) for s in self.subs)

    @property
    def oldest_deadline(self) -> float:
        return min(s.deadline for s in self.subs)


# -- the plane ---------------------------------------------------------------


class BatchPlane:
    """The shared scheduler.  One instance per process (`get_plane()`);
    tests construct their own to control knobs and lifetime."""

    def __init__(self, target_lanes: int | None = None,
                 max_flush_lanes: int | None = None):
        # a batch is FULL (ships immediately) at target_lanes; one flush
        # never takes more than max_flush_lanes (fairness truncation)
        self.target_lanes = (target_lanes if target_lanes is not None
                             else _env_int("TM_BATCHPLANE_LANES", 1024))
        self.max_flush_lanes = (
            max_flush_lanes if max_flush_lanes is not None
            else _env_int("TM_BATCHPLANE_MAX_FLUSH", 4096))
        self._cond = threading.Condition()
        self._pending: dict[tuple, _PendingBatch] = {}
        self._thread: threading.Thread | None = None
        self._stopped = False
        self._inflight = 0          # submissions being executed right now

    # -- submission entry points ----------------------------------------

    def _submit(self, kind, key, producer, klass, arrays, n,
                max_wait: float | None) -> Submission:
        wait_s = class_max_wait(klass) if max_wait is None else max_wait
        sub = Submission(kind, key, producer, klass,
                         time.perf_counter() + wait_s, arrays, n)
        if not enabled():
            self._execute([sub], reason="inline")
            return sub
        with self._cond:
            if self._stopped:
                raise RuntimeError("batch plane is stopped")
            batch = self._pending.get(key)
            if batch is None:
                batch = self._pending[key] = _PendingBatch(key)
            batch.add(sub)
            self._ensure_worker()
            self._cond.notify_all()
        return sub

    def submit_grouped(self, set_key: bytes, val_pubs, val_idx, msgs,
                       sigs, *, producer: str, klass: str,
                       max_wait: float | None = None) -> Submission:
        n = len(val_idx)
        key = ("grouped", bytes(set_key), msgs.shape[-1] if n else 0)
        arrays = (val_pubs, np.asarray(val_idx, np.int32),
                  np.asarray(msgs), np.asarray(sigs))
        return self._submit("grouped", key, producer, klass, arrays, n,
                            max_wait)

    def submit_templated(self, set_key: bytes, val_pubs, val_idx,
                         tmpl_idx, templates, sigs, *, producer: str,
                         klass: str,
                         max_wait: float | None = None) -> Submission:
        n = len(val_idx)
        key = ("templated", bytes(set_key),
               templates.shape[-1] if len(templates) else 0)
        arrays = (val_pubs, np.asarray(val_idx, np.int32),
                  np.asarray(tmpl_idx, np.int32), np.asarray(templates),
                  np.asarray(sigs))
        return self._submit("templated", key, producer, klass, arrays, n,
                            max_wait)

    def submit_raw(self, pubkeys, msgs, sigs, *, producer: str,
                   klass: str, max_wait: float | None = None) -> Submission:
        """Per-lane ed25519 verify (pubkeys NOT from a fixed set): the
        mempool CheckTx lane.  Raw lanes merge across ALL producers."""
        n = len(sigs)
        key = ("raw", msgs.shape[-1] if n else 0)
        arrays = (np.asarray(pubkeys), np.asarray(msgs), np.asarray(sigs))
        return self._submit("raw", key, producer, klass, arrays, n,
                            max_wait)

    def submit_secp(self, items: list[tuple[bytes, bytes, bytes]], *,
                    producer: str, klass: str,
                    max_wait: float | None = None) -> Submission:
        """secp256k1 lanes as (pub33, msg, der_sig) tuples — coalesced
        into one host-side OpenSSL pass (no device kernel yet; same
        queue discipline so one slots in without touching producers)."""
        key = ("secp",)
        return self._submit("secp", key, producer, klass,
                            (list(items),), len(items), max_wait)

    # -- worker ---------------------------------------------------------

    def _ensure_worker(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="batchplane", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stopped:
                    self._cond.wait()
                if self._stopped and not self._pending:
                    return
                batch, reason = self._next_flush_locked()
                if batch is None:
                    # nothing due yet: sleep until the earliest deadline
                    horizon = min(b.oldest_deadline
                                  for b in self._pending.values())
                    self._cond.wait(
                        max(horizon - time.perf_counter(), 1e-4))
                    continue
                subs = self._take_locked(batch)
                self._inflight += len(subs)
                depth = sum(len(b.subs) for b in self._pending.values())
            REGISTRY.batchplane_queue_depth_hist.observe(depth)
            try:
                self._execute(subs, reason)
            finally:
                with self._cond:
                    self._inflight -= len(subs)
                    self._cond.notify_all()

    def _next_flush_locked(self):
        """(batch, reason) to flush now, or (None, None) if nothing is
        full or due.  Full batches beat due batches; among candidates
        the highest class wins, then the oldest deadline — consensus
        preempts even an earlier-queued light batch."""
        now = time.perf_counter()
        full = [b for b in self._pending.values()
                if b.lanes >= self.target_lanes]
        due = [b for b in self._pending.values()
               if b.oldest_deadline <= now]
        pick = lambda bs: min(              # noqa: E731 (tiny chooser)
            bs, key=lambda b: (b.priority, b.oldest_deadline))
        if full:
            return pick(full), "full"
        if due:
            return pick(due), "deadline"
        return None, None

    def _take_locked(self, batch: _PendingBatch) -> list[Submission]:
        """Remove up to max_flush_lanes from `batch`, round-robin across
        producers so no producer starves out of a truncated flush."""
        if batch.lanes <= self.max_flush_lanes:
            del self._pending[batch.key]
            return batch.subs
        by_producer: dict[str, list[Submission]] = {}
        for s in batch.subs:
            by_producer.setdefault(s.producer, []).append(s)
        taken, lanes = [], 0
        queues = list(by_producer.values())
        while queues and lanes < self.max_flush_lanes:
            for q in list(queues):
                if not q:
                    queues.remove(q)
                    continue
                nxt = q[0]
                if taken and lanes + nxt.n > self.max_flush_lanes:
                    queues.remove(q)      # would overflow; producer done
                    continue
                taken.append(q.pop(0))
                lanes += nxt.n
        left = [s for s in batch.subs if s not in taken]
        if left:
            nb = _PendingBatch(batch.key)
            for s in left:
                nb.add(s)
            self._pending[batch.key] = nb
        else:
            del self._pending[batch.key]
        # keep arrival order within the flush (stable lane slicing)
        taken.sort(key=lambda s: s.enq_t)
        return taken

    # -- execution ------------------------------------------------------

    def _execute(self, subs: list[Submission], reason: str) -> None:
        now = time.perf_counter()
        producers = {s.producer for s in subs}
        lanes = sum(s.n for s in subs)
        REGISTRY.batchplane_flushes.inc()
        REGISTRY.batchplane_flush_reason.labels(reason).inc()
        if len(producers) > 1:
            REGISTRY.batchplane_mixed_batches.inc()
        for s in subs:
            REGISTRY.batchplane_wait_seconds.labels(s.klass).observe(
                max(now - s.enq_t, 0.0))
            REGISTRY.batchplane_lanes.labels(s.producer).inc(s.n)
        if lanes:
            REGISTRY.batchplane_occupancy_hist.observe(
                lanes / float(_chunk(max(lanes, 1))))
        try:
            kind = subs[0].kind
            if kind == "grouped":
                out = self._run_grouped(subs)
            elif kind == "templated":
                out = self._run_templated(subs)
            elif kind == "raw":
                out = self._run_raw(subs)
            else:
                out = self._run_secp(subs)
        except BaseException as e:                # DeviceFault included:
            for s in subs:                        # blame ONLY this flush
                s._fail(e)
            return
        off = 0
        for s in subs:
            s._resolve(out[off:off + s.n])
            off += s.n

    @staticmethod
    def _run_grouped(subs) -> np.ndarray:
        from tendermint_tpu.crypto import backend as cb
        set_key = subs[0].key[1]
        val_pubs = subs[0].arrays[0]
        idx = np.concatenate([s.arrays[1] for s in subs])
        msgs = np.concatenate([s.arrays[2] for s in subs])
        sigs = np.concatenate([s.arrays[3] for s in subs])
        return cb.verify_grouped(set_key, val_pubs, idx, msgs, sigs)

    @staticmethod
    def _run_templated(subs) -> np.ndarray:
        from tendermint_tpu.crypto import backend as cb
        set_key = subs[0].key[1]
        val_pubs = subs[0].arrays[0]
        # rebase each submission's template indices onto the combined
        # template block (the merge_commit_lanes layout)
        t_off, tmpl_parts, idx_parts = 0, [], []
        for s in subs:
            _vp, _vi, ti, templates, _sg = s.arrays
            idx_parts.append(ti + t_off)
            tmpl_parts.append(templates)
            t_off += len(templates)
        idx = np.concatenate([s.arrays[1] for s in subs])
        tmpl_idx = np.concatenate(idx_parts)
        templates = np.concatenate(tmpl_parts)
        sigs = np.concatenate([s.arrays[4] for s in subs])
        return cb.verify_grouped_templated(set_key, val_pubs, idx,
                                           tmpl_idx, templates, sigs)

    @staticmethod
    def _run_raw(subs) -> np.ndarray:
        from tendermint_tpu.crypto import backend as cb
        pubs = np.concatenate([s.arrays[0] for s in subs])
        msgs = np.concatenate([s.arrays[1] for s in subs])
        sigs = np.concatenate([s.arrays[2] for s in subs])
        return cb.verify_batch(pubs, msgs, sigs)

    @staticmethod
    def _run_secp(subs) -> np.ndarray:
        from tendermint_tpu.crypto import secp256k1
        out = []
        for s in subs:
            for pub, msg, sig in s.arrays[0]:
                out.append(
                    secp256k1.PubKeySecp256k1(pub).verify(msg, sig))
        return np.asarray(out, dtype=bool)

    # -- lifecycle / introspection --------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until the queue AND in-flight work are empty (tests,
        clean shutdown).  True when drained, False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._pending or self._inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.notify_all()
                self._cond.wait(min(left, 0.05))
        return True

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    def depth(self) -> int:
        with self._cond:
            return sum(len(b.subs) for b in self._pending.values())

    def class_depth(self, klass: str) -> int:
        """Pending LANES carrying `klass` submissions.  The mempool's
        admission backpressure probes this before verifying: when the
        mempool class already queues more lanes than it can drain, new
        enveloped txs are rejected at the front door instead of growing
        the queue under the consensus class."""
        with self._cond:
            return sum(s.n for b in self._pending.values()
                       for s in b.subs if s.klass == klass)


def _chunk(n: int) -> int:
    """The padded chunk size `n` lanes will ride (the backend's
    power-of-2 bucket) — the denominator of plane-level occupancy."""
    from tendermint_tpu.crypto.backend import _bucket
    return _bucket(n)


# -- process-wide singleton --------------------------------------------------

_PLANE: BatchPlane | None = None
_PLANE_LOCK = threading.Lock()


def get_plane() -> BatchPlane:
    global _PLANE
    with _PLANE_LOCK:
        if _PLANE is None:
            _PLANE = BatchPlane()
        return _PLANE


def reset_plane() -> None:
    """Stop and discard the singleton (tests; chaos rigs between runs)."""
    global _PLANE
    with _PLANE_LOCK:
        if _PLANE is not None:
            _PLANE.stop()
            _PLANE = None


# -- synchronous producer wrappers ------------------------------------------
#
# Drop-in equivalents of the crypto.backend module helpers, routed
# through the plane.  Producers call THESE; tmlint's `batchplane` rule
# flags direct backend calls from consensus/light/mempool/blockchain.


def verify_grouped(set_key: bytes, val_pubs, val_idx, msgs, sigs, *,
                   producer: str, klass: str,
                   max_wait: float | None = None) -> np.ndarray:
    return get_plane().submit_grouped(
        set_key, val_pubs, val_idx, msgs, sigs, producer=producer,
        klass=klass, max_wait=max_wait).wait()


def verify_grouped_templated(set_key: bytes, val_pubs, val_idx, tmpl_idx,
                             templates, sigs, *, producer: str,
                             klass: str,
                             max_wait: float | None = None) -> np.ndarray:
    return get_plane().submit_templated(
        set_key, val_pubs, val_idx, tmpl_idx, templates, sigs,
        producer=producer, klass=klass, max_wait=max_wait).wait()


def verify_batch(pubkeys, msgs, sigs, *, producer: str, klass: str,
                 max_wait: float | None = None) -> np.ndarray:
    return get_plane().submit_raw(
        pubkeys, msgs, sigs, producer=producer, klass=klass,
        max_wait=max_wait).wait()


def verify_secp(items: list[tuple[bytes, bytes, bytes]], *, producer: str,
                klass: str, max_wait: float | None = None) -> np.ndarray:
    return get_plane().submit_secp(
        items, producer=producer, klass=klass, max_wait=max_wait).wait()
