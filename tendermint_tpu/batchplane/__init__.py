"""Unified device batch plane — one verify scheduler for every
workload (see `scheduler.py` for the full contract).

Producers import THIS surface instead of calling `crypto.backend`
directly; tmlint's `batchplane` rule enforces it for the hot-path
modules (consensus/, light/, mempool/, blockchain/).
"""

from tendermint_tpu.batchplane.scheduler import (BatchPlane,
                                                 CLASS_CONSENSUS,
                                                 CLASS_FASTSYNC,
                                                 CLASS_LIGHT,
                                                 CLASS_MEMPOOL,
                                                 CLASS_PRIORITY,
                                                 Submission, enabled,
                                                 get_plane, reset_plane,
                                                 verify_batch,
                                                 verify_grouped,
                                                 verify_grouped_templated,
                                                 verify_secp)

__all__ = ["BatchPlane", "CLASS_CONSENSUS", "CLASS_FASTSYNC",
           "CLASS_LIGHT", "CLASS_MEMPOOL", "CLASS_PRIORITY",
           "Submission", "enabled", "get_plane", "reset_plane",
           "verify_batch", "verify_grouped", "verify_grouped_templated",
           "verify_secp"]
