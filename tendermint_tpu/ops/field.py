"""Batched GF(2^255-19) arithmetic for TPU in radix-2^8 int32 limbs.

TPU has no native 64-bit integer multiply, so field elements are held as 32
little-endian limbs of 8 bits each in an int32 lane (shape `[..., 32]`).

Representation invariant ("normalized"): |limb| <= 512.  Carry propagation
is done with *parallel* vector passes (shift the carry vector by one limb,
fold the 2^256 overflow back with x38) instead of a 32-step sequential
chain — interval analysis (executable: tests/test_field.py
`test_carry_pass_counts_preserve_invariant`) shows 4 passes re-establish
the invariant after a schoolbook product (columns <= 32*512^2*39 < 2^31,
exact in int32) and 2 passes after add/sub.  This
keeps both the XLA graph and the critical path shallow.

All functions are shape-polymorphic over leading batch dims and jit/vmap
friendly (static shapes, no data-dependent control flow).

This is the substrate for the batch ed25519 verifier that replaces the
reference's scalar per-vote verify (reference `types/vote_set.go:175`,
`types/validator_set.go:247-249`).
"""

from __future__ import annotations

import jax
import numpy as np
import jax.numpy as jnp

NLIMBS = 32
RADIX = 8
MASK = (1 << RADIX) - 1

P = 2**255 - 19
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)


def int_to_limbs(x: int) -> np.ndarray:
    """Python int (0 <= x < 2^256) -> np.int32[32] little-endian limbs."""
    assert 0 <= x < 2**256
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMBS)],
                    dtype=np.int32)


def limbs_to_int(limbs) -> int:
    arr = np.asarray(limbs)
    return sum(int(arr[..., i]) << (RADIX * i) for i in range(NLIMBS))


def const(x: int) -> jnp.ndarray:
    return jnp.asarray(int_to_limbs(x))


# 8p in a 32-limb representation with small limbs (8p >= 2^256 so the
# canonical byte representation does not exist; limbs [104, 255.., 1023]
# sum to exactly 2^258 - 152).  Added before subtraction so the value stays
# nonnegative for any normalized subtrahend.
_EIGHT_P = np.full(NLIMBS, 255, dtype=np.int32)
_EIGHT_P[0] = 104
_EIGHT_P[31] = 1023
assert sum(int(v) << (8 * i) for i, v in enumerate(_EIGHT_P)) == 8 * P

_P_LIMBS = int_to_limbs(P)
# 2^256 - p = 2^255 + 19: the complement used for parallel conditional
# subtraction (x >= p  <=>  x + (2^256 - p) carries out of limb 31).
_NEG_P = np.zeros(NLIMBS, dtype=np.int32)
_NEG_P[0] = 19
_NEG_P[31] = 128


def carry(x: jnp.ndarray, passes: int = 4) -> jnp.ndarray:
    """Parallel carry: `passes` rounds of  x -> (x & 255) + shift(x >> 8),
    with the limb-31 carry folded into limb 0 via 2^256 = 38 (mod p).

    Exact for |limb| < 2^31 / 39; arithmetic right shift gives floor
    division so negative limbs are handled.  Re-establishes |limb| <= 512
    given enough passes for the input bound (4 covers a schoolbook product,
    2 covers one add/sub of normalized values).
    """
    for _ in range(passes):
        c = x >> RADIX
        x = x & MASK
        x = x.at[..., 1:].add(c[..., :-1])
        x = x.at[..., 0].add(c[..., -1] * 38)
    return x


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b, passes=2)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a - b + jnp.asarray(_EIGHT_P), passes=2)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return carry(jnp.asarray(_EIGHT_P) - a, passes=2)


def _fold_carry(acc: jnp.ndarray) -> jnp.ndarray:
    """Fold product columns 32..62 by 38 (2^256 = 38 mod p) and carry."""
    lo = acc[..., :NLIMBS]
    hi = acc[..., NLIMBS:]
    lo = lo.at[..., :NLIMBS - 1].add(hi * 38)
    return carry(lo, passes=4)


# Fixed anti-diagonal scatter: column k of M sums outer-product entries
# (i, j) with i + j == k, turning the limb product into one MXU matmul.
_ADIAG = np.zeros((NLIMBS * NLIMBS, 2 * NLIMBS - 1), np.float32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _ADIAG[_i * NLIMBS + _j, _i + _j] = 1.0


def mul_basic(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook product as outer-product + one f32 matmul — the
    compile-cheap path.

    The elementwise outer [..., 32, 32] (entries <= 512^2, f32-exact) is
    contracted against the fixed 0/1 anti-diagonal matrix on the MXU with
    Precision.HIGHEST (full f32: column sums <= 32*512^2 < 2^24 stay
    exact; the TPU default bf16 passes would truncate).  XLA compiles a
    plain dot in well under a second where the previous padded-row
    formulation (32 pads + stack + sum per mul) ballooned chain graphs —
    a 20-mul chain measured 69s to compile vs 5s for this form, which is
    what made the 10-bit comb build pay 130s+ of jit (VERDICT r4 #3).
    Works for any rank (the conv form's >2-d Mosaic SIGABRT does not
    apply); runtime is within ~25% of the conv on 2-d shapes, so the
    conv stays the hot-verify mul and this serves everything else.
    """
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    af = jnp.broadcast_to(a, shape).astype(jnp.float32)
    bf = jnp.broadcast_to(b, shape).astype(jnp.float32)
    outer = (af[..., :, None] * bf[..., None, :]).reshape(
        shape[:-1] + (NLIMBS * NLIMBS,))
    prod = jax.lax.dot_general(
        outer, jnp.asarray(_ADIAG), (((outer.ndim - 1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)
    return _fold_carry(prod.astype(jnp.int32))


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook 32x32 limb product with fold of columns 32..62 by 38.

    For flat batches the product is ONE batch-grouped convolution in f32
    (every lane convolves with its own 32-tap filter): with both operands
    under the |limb| <= 512 invariant every column sum is below
    32*512*512 < 2^24, so f32 accumulation is exact, and
    `Precision.HIGHEST` pins the TPU conv to f32-faithful passes.  The
    conv edges out `mul_basic`'s matmul form by ~25% at steady state but
    costs ~4x more XLA compile time, so it serves only the flat hot-path
    shapes: big 2-d batches.  Small batches (< 4096 lanes — table-build
    chains over V validators, recursion totals) take `mul_basic`, where
    runtime is negligible and compile time is what hurts; shapes deeper
    than 2-d also fall back (the conv+reshape combination SIGABRTs the
    TPU compiler there).
    """
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    flat = 1
    for d in shape[:-1]:
        flat *= d
    if len(shape) > 2 or flat < 4096:
        return mul_basic(a, b)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    n = 1
    for d in shape[:-1]:
        n *= d
    lhs = a.astype(jnp.float32).reshape(n, 1, NLIMBS)
    rhs = jnp.flip(b.astype(jnp.float32), -1).reshape(n, 1, NLIMBS)
    out = jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1,),
        padding=[(NLIMBS - 1, NLIMBS - 1)],
        batch_group_count=n, precision=jax.lax.Precision.HIGHEST)
    return _fold_carry(out.reshape(shape[:-1] + (2 * NLIMBS - 1,))
                       .astype(jnp.int32))


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small constant (normalized a, k <= 4)."""
    assert 1 <= k <= 4
    return carry(a * k, passes=2)


def _pow_const(z: jnp.ndarray, exp: int) -> jnp.ndarray:
    """z^exp via one square-and-multiply scan over the static bit string.

    ~2x the multiplies of the ref10 addition chain (508 vs 265 for p-2),
    but the whole ladder is ONE two-mul scan body for XLA — the chain's
    ~30 distinct mul/fori sites were several seconds of compile at every
    ladder call site (decompress, batch inversion), and ladders run
    either on tiny shapes (V keys, recursion totals) or once per batch,
    so the extra multiplies are noise at runtime.
    """
    bits = jnp.asarray(np.array([int(b) for b in bin(exp)[3:]], np.bool_))

    def body(acc, bit):
        acc = mul_basic(acc, acc)
        return jnp.where(bit, mul_basic(acc, z), acc), None

    acc, _ = jax.lax.scan(body, z, bits)
    return acc


def inv(z: jnp.ndarray) -> jnp.ndarray:
    """z^(p-2) = z^(2^255 - 21)."""
    return _pow_const(z, P - 2)


def pow22523(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252 - 3)."""
    return _pow_const(z, (P - 5) // 8)


def _batch_inv_nonzero(z: jnp.ndarray) -> jnp.ndarray:
    """Blocked Montgomery inversion of NONZERO [N, 32] values.

    Reshapes to [K, C] columns and runs two lax.scan product sweeps whose
    body is a single `mul` — the traced graph stays tiny regardless of N
    (a log-depth associative_scan here made XLA compile for minutes) —
    then recurses on the C column totals until a small unrolled base.
    Work is still ~5 muls per lane; sequential depth is ~2*sqrt pieces.
    """
    n = z.shape[0]
    one = jnp.asarray(int_to_limbs(1))
    if n <= 8:
        # unrolled exclusive prefix/suffix products + one inversion ladder
        pre, acc = [], jnp.broadcast_to(one, z.shape[-1:])
        for i in range(n):
            pre.append(acc)
            acc = mul_basic(acc, z[i]) if i < n - 1 else acc
        suf, acc = [None] * n, jnp.broadcast_to(one, z.shape[-1:])
        for i in range(n - 1, -1, -1):
            suf[i] = acc
            acc = mul_basic(acc, z[i])
        tinv = inv(acc)          # acc == product of all lanes
        return jnp.stack([mul_basic(mul_basic(pre[i], suf[i]), tinv)
                          for i in range(n)])
    c = 1 << (max(n, 4).bit_length() // 2)       # columns ~ sqrt(n)
    k = -(-n // c)
    pad = k * c - n
    zs = jnp.concatenate(
        [z, jnp.broadcast_to(one, (pad, NLIMBS))]) if pad else z
    cols = zs.reshape(k, c, NLIMBS)

    def fwd(carry, row):
        return mul_basic(carry, row), carry      # ys = EXCLUSIVE prefix
    ones_c = jnp.broadcast_to(one, (c, NLIMBS))
    total, pre_ex = jax.lax.scan(fwd, ones_c, cols)
    _, suf_ex_rev = jax.lax.scan(fwd, ones_c, cols[::-1])
    suf_ex = suf_ex_rev[::-1]
    tinv = _batch_inv_nonzero(total)             # recurse on [C] totals
    zi = mul_basic(mul_basic(pre_ex, suf_ex), tinv[None, :, :])
    return zi.reshape(k * c, NLIMBS)[:n]


def batch_inv(z: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Montgomery batch inversion over the leading axis.

    z int32[N, 32] -> (z^-1 int32[N, 32], nonzero bool[N]).  One ~265-mul
    inversion ladder amortizes over the whole batch; per-lane cost is ~5
    muls.  Lanes with z == 0 (no inverse) return 0 and are flagged False —
    they are masked to 1 internally so they cannot zero a running product
    and poison the rest of the batch.
    """
    nz = ~is_zero(z)
    one = jnp.asarray(int_to_limbs(1))
    zs = jnp.where(nz[..., None], z, one)
    zi = _batch_inv_nonzero(zs)
    return jnp.where(nz[..., None], zi, 0), nz


def ks_prefix(g: jnp.ndarray, p: jnp.ndarray) -> jnp.ndarray:
    """Kogge-Stone scan of the carry-lookahead monoid over the limb axis.

    g[i] = limb i generates a carry on its own; p[i] = limb i propagates
    an incoming carry.  Returns G[i] = carry OUT of limb i given carry-in
    0 to limb 0 — log2(n) parallel steps instead of an n-step chain.
    """
    n = g.shape[-1]
    G, Pp = g, p
    sh = 1
    while sh < n:
        pad = [(0, 0)] * (g.ndim - 1) + [(sh, 0)]
        Gs = jnp.pad(G[..., :-sh], pad)
        Ps = jnp.pad(Pp[..., :-sh], pad)
        G = G | (Pp & Gs)
        Pp = Pp & Ps
        sh *= 2
    return G


def _carry_in(G: jnp.ndarray) -> jnp.ndarray:
    """Carry INTO each limb from the inclusive carry-out scan."""
    pad = [(0, 0)] * (G.ndim - 1) + [(1, 0)]
    return jnp.pad(G[..., :-1], pad)


def ks_normalize(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact byte normalization of limbs in [0, 510] via carry lookahead.

    Returns (bytes in [0,255], carry_out in {0,1}).  Limbs <= 510 keep
    every carry in {0,1}: generate iff limb >= 256, propagate iff
    limb >= 255.
    """
    G = ks_prefix(x >= 256, x >= 255)
    r = (x + _carry_in(G).astype(x.dtype)) & MASK
    return r, G[..., -1].astype(x.dtype)


def ks_sub_const(x: jnp.ndarray, c: jnp.ndarray) -> tuple:
    """(x - c) per byte limb with borrow lookahead.

    x limbs in [0, 255+eps], c limbs in [0, 255].  Returns (diff bytes,
    borrow_out in {0,1}): borrow generates iff x_i < c_i, propagates iff
    x_i <= c_i.
    """
    B = ks_prefix(x < c, x <= c)
    r = (x - c - _carry_in(B).astype(x.dtype)) & MASK
    return r, B[..., -1].astype(x.dtype)


_E40 = 40  # per-limb lift clearing the [-39, +] residual range


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Fully reduce to the canonical representative in [0, p), limbs [0,255].

    Fully parallel (VERDICT r3: the sequential 64-step carry chain here
    was ~20% of the grouped-verify step): parallel carry passes leave
    limbs in [-39, 333]; lifting by +40 per limb makes them nonnegative
    for an exact Kogge-Stone normalize, a borrow-lookahead subtraction
    takes the lift back out, the net 2^256 wrap folds by 38, and two
    complement-add rounds conditionally subtract p.  Requires value >= 0
    (all library ops preserve nonnegative values).
    """
    x = carry(x, passes=4)                 # limbs [-39, 333], value < 1.5*2^256
    b, t1 = ks_normalize(x + _E40)         # bytes of value + 40*(2^256-1)/255
    r, t2 = ks_sub_const(b, jnp.full_like(b, _E40))
    x = r.at[..., 0].add((t1 - t2) * 38)   # net wrap in {0,1}: fold 2^256 = 38
    b2, t = ks_normalize(x)                # round 2 clears the +38 on limb 0
    x = b2.at[..., 0].add(t * 38)
    # value < 2^256 < 2p + 39: conditionally subtract p twice via the
    # complement: x >= p  <=>  x + (2^256 - p) carries out of limb 31
    neg_p = jnp.asarray(_NEG_P)
    for _ in range(2):
        s, t3 = ks_normalize(x + neg_p)
        x = jnp.where((t3 == 1)[..., None], s, x)
    return x


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """Boolean [...,] mask: x == 0 mod p."""
    return jnp.all(canonical(x) == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return is_zero(sub(a, b))


def parity(x: jnp.ndarray) -> jnp.ndarray:
    """LSB of the canonical representative (the ed25519 sign bit source)."""
    return canonical(x)[..., 0] & 1


def to_bytes(x: jnp.ndarray) -> jnp.ndarray:
    """Canonical little-endian 32-byte encoding, uint8[..., 32]."""
    return canonical(x).astype(jnp.uint8)


def from_bytes(b: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., 32] -> limbs (radix 2^8 means bytes are the limbs)."""
    return b.astype(jnp.int32)
