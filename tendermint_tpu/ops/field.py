"""Batched GF(2^255-19) arithmetic for TPU in radix-2^8 int32 limbs.

TPU has no native 64-bit integer multiply, so field elements are held as 32
little-endian limbs of 8 bits each in an int32 lane (shape `[..., 32]`).
Schoolbook products of 8-bit limbs are <= 2^16 and a 32-term column sum plus
the 19*2 fold stays below 2^29, comfortably inside int32 — every op is exact.
All functions are shape-polymorphic over leading batch dims and jit/vmap
friendly (static shapes, no data-dependent control flow).

This is the substrate for the batch ed25519 verifier that replaces the
reference's scalar per-vote verify (reference `types/vote_set.go:175`,
`types/validator_set.go:247-249`).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

NLIMBS = 32
RADIX = 8
MASK = (1 << RADIX) - 1

P = 2**255 - 19
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)


def int_to_limbs(x: int) -> np.ndarray:
    """Python int (0 <= x < 2^256) -> np.int32[32] little-endian limbs."""
    assert 0 <= x < 2**256
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMBS)],
                    dtype=np.int32)


def limbs_to_int(limbs) -> int:
    arr = np.asarray(limbs)
    return sum(int(arr[..., i]) << (RADIX * i) for i in range(NLIMBS))


def const(x: int) -> jnp.ndarray:
    return jnp.asarray(int_to_limbs(x))


# 8p in a 32-limb representation with small limbs (8p >= 2^256 so the
# canonical byte representation does not exist; limbs [104, 255.., 1023]
# sum to exactly 2^258 - 152).  Added before subtraction to keep limbs
# nonnegative for any minuend with limbs < 2^9.
_EIGHT_P = np.full(NLIMBS, 255, dtype=np.int32)
_EIGHT_P[0] = 104
_EIGHT_P[31] = 1023
assert sum(int(v) << (8 * i) for i, v in enumerate(_EIGHT_P)) == 8 * P

_P_LIMBS = int_to_limbs(P)


def carry(x: jnp.ndarray) -> jnp.ndarray:
    """Normalize limbs to [0, 2^9): two carry passes with 2^256 = 38 folds.

    Accepts limbs in (-2^30, 2^30); arithmetic right shift gives floor
    division so negative intermediate limbs are handled.
    """
    for _ in range(2):
        outs = []
        c = jnp.zeros_like(x[..., 0])
        for i in range(NLIMBS):
            v = x[..., i] + c
            c = v >> RADIX
            outs.append(v & MASK)
        x = jnp.stack(outs, axis=-1)
        x = x.at[..., 0].add(c * 38)
    return x


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a + b)


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return carry(a - b + jnp.asarray(_EIGHT_P))


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return carry(jnp.asarray(_EIGHT_P) - a)


def mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Schoolbook 32x32 limb product with fold of columns 32..62 by 38."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    acc = jnp.zeros(shape[:-1] + (2 * NLIMBS - 1,), dtype=jnp.int32)
    for i in range(NLIMBS):
        acc = acc.at[..., i:i + NLIMBS].add(a[..., i:i + 1] * b)
    lo = acc[..., :NLIMBS]
    hi = acc[..., NLIMBS:]
    lo = lo.at[..., :NLIMBS - 1].add(hi * 38)
    return carry(lo)


def sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mul(a, a)


def mul_small(a: jnp.ndarray, k: int) -> jnp.ndarray:
    """Multiply by a small constant (k < 2^20)."""
    return carry(a * k)


def _nsqr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    for _ in range(n):
        x = sqr(x)
    return x


def _pow_core(z: jnp.ndarray):
    """Shared ladder: returns (z^(2^250-1), z^11, z^(2^50-1), z^(2^100-1))."""
    z2 = sqr(z)
    z9 = mul(_nsqr(z2, 2), z)
    z11 = mul(z9, z2)
    z_5_0 = mul(sqr(z11), z9)               # z^(2^5 - 1)
    z_10_0 = mul(_nsqr(z_5_0, 5), z_5_0)    # z^(2^10 - 1)
    z_20_0 = mul(_nsqr(z_10_0, 10), z_10_0)
    z_40_0 = mul(_nsqr(z_20_0, 20), z_20_0)
    z_50_0 = mul(_nsqr(z_40_0, 10), z_10_0)
    z_100_0 = mul(_nsqr(z_50_0, 50), z_50_0)
    z_200_0 = mul(_nsqr(z_100_0, 100), z_100_0)
    z_250_0 = mul(_nsqr(z_200_0, 50), z_50_0)
    return z_250_0, z11


def inv(z: jnp.ndarray) -> jnp.ndarray:
    """z^(p-2) = z^(2^255 - 21) via the ref10-style addition chain."""
    z_250_0, z11 = _pow_core(z)
    return mul(_nsqr(z_250_0, 5), z11)


def pow22523(z: jnp.ndarray) -> jnp.ndarray:
    """z^((p-5)/8) = z^(2^252 - 3)."""
    z_250_0, _ = _pow_core(z)
    return mul(_nsqr(z_250_0, 2), z)


def canonical(x: jnp.ndarray) -> jnp.ndarray:
    """Fully reduce to the canonical representative in [0, p), limbs [0,255]."""
    x = carry(x)
    # after carry limbs < 2^9 and limb0 may hold the +38 fold; one more
    # fold-free pass brings every limb to [0,255] with zero carry-out ...
    x = carry(x)
    outs, c = [], jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        v = x[..., i] + c
        c = v >> RADIX
        outs.append(v & MASK)
    x = jnp.stack(outs, axis=-1)
    x = x.at[..., 0].add(c * 38)
    outs, c = [], jnp.zeros_like(x[..., 0])
    for i in range(NLIMBS):
        v = x[..., i] + c
        c = v >> RADIX
        outs.append(v & MASK)
    x = jnp.stack(outs, axis=-1)
    # now x < 2^256: conditionally subtract p twice
    p_l = jnp.asarray(_P_LIMBS)
    for _ in range(2):
        outs, borrow = [], jnp.zeros_like(x[..., 0])
        for i in range(NLIMBS):
            v = x[..., i] - p_l[i] - borrow
            borrow = (v < 0).astype(jnp.int32)
            outs.append(v + (borrow << RADIX))
        diff = jnp.stack(outs, axis=-1)
        ge = (borrow == 0)[..., None]
        x = jnp.where(ge, diff, x)
    return x


def is_zero(x: jnp.ndarray) -> jnp.ndarray:
    """Boolean [...,] mask: x == 0 mod p."""
    return jnp.all(canonical(x) == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return is_zero(sub(a, b))


def parity(x: jnp.ndarray) -> jnp.ndarray:
    """LSB of the canonical representative (the ed25519 sign bit source)."""
    return canonical(x)[..., 0] & 1


def to_bytes(x: jnp.ndarray) -> jnp.ndarray:
    """Canonical little-endian 32-byte encoding, uint8[..., 32]."""
    return canonical(x).astype(jnp.uint8)


def from_bytes(b: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., 32] -> limbs (radix 2^8 means bytes are the limbs)."""
    return b.astype(jnp.int32)
