"""Batched SHA-256 on TPU (uint32 lanes, static shapes).

Replaces the reference's scalar Merkle/part hashing (reference
`types/part_set.go:32-41`, `types/tx.go:29-43` — RIPEMD-160 in that era; this
framework standardizes on SHA-256, see `tendermint_tpu.types.merkle`).
Message length must be static; the whole batch is hashed in lockstep, one
compression round loop shared across the batch — exactly the shape the VPU
wants.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2], dtype=np.uint32)

_H0 = np.array([0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19], dtype=np.uint32)


def _rotr(x, n):
    return (x >> np.uint32(n)) | (x << np.uint32(32 - n))


def pad(nbytes: int) -> np.ndarray:
    """The static SHA-256 padding suffix for an nbytes message (uint8[...])."""
    padlen = (56 - (nbytes + 1)) % 64
    tail = np.zeros(1 + padlen + 8, dtype=np.uint8)
    tail[0] = 0x80
    bits = nbytes * 8
    for i in range(8):
        tail[-1 - i] = (bits >> (8 * i)) & 0xFF
    return tail


_UNROLL = 16      # rounds per scan step: graph size vs carry traffic knob


def _compress(state, w16):
    """One compression: lax.scan over round groups, _UNROLL rounds
    unrolled per step, with the message schedule as a ROLLING 16-word
    window in the carry.

    The window trick removes the [..., 64] schedule array and its
    per-round dynamic indexing along the vector lane dim (the original
    HBM-bound formulation); the partial unroll keeps the traced graph
    small enough for XLA's CPU backend to compile in seconds (a fully
    unrolled 64-round body took minutes of LLVM time) while the carry
    (8 state + 16 window words) round-trips only once per 16 rounds.
    At round i the window holds w[i..i+15]: consume window[0], generate
    w[i+16] = w[i] + s0(w[i+1]) + w[i+9] + s1(w[i+14]), shift.
    """
    ks = jnp.asarray(_K.reshape(64 // _UNROLL, _UNROLL))

    def step(carry, k):
        a, b, c, d, e, f, g, h = carry[:8]
        w = list(carry[8:])
        for j in range(_UNROLL):
            wi = w[0]
            ws0 = _rotr(w[1], 7) ^ _rotr(w[1], 18) ^ (w[1] >> np.uint32(3))
            ws1 = (_rotr(w[14], 17) ^ _rotr(w[14], 19)
                   ^ (w[14] >> np.uint32(10)))
            w = w[1:] + [w[0] + ws0 + w[9] + ws1]
            s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = (e & f) ^ (~e & g)
            t1 = h + s1 + ch + k[j] + wi
            s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            maj = (a & b) ^ (a & c) ^ (b & c)
            a, b, c, d, e, f, g, h = (t1 + s0 + maj, a, b, c,
                                      d + t1, e, f, g)
        return (a, b, c, d, e, f, g, h) + tuple(w), None

    init = tuple(state) + tuple(w16[..., i] for i in range(16))
    out, _ = jax.lax.scan(step, init, ks)
    return tuple(s + n for s, n in zip(state, out[:8]))


def sha256_blocks(blocks: jnp.ndarray) -> jnp.ndarray:
    """Hash pre-padded big-endian words uint32[B, nblocks, 16] -> uint32[B, 8]."""
    nblocks = blocks.shape[-2]
    state = tuple(jnp.broadcast_to(jnp.uint32(h), blocks.shape[:-2])
                  for h in _H0)
    for i in range(nblocks):
        state = _compress(state, blocks[..., i, :])
    return jnp.stack(state, axis=-1)


def bytes_to_words(msg: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., 64*n] -> big-endian uint32[..., n, 16]."""
    n = msg.shape[-1] // 64
    b = msg.reshape(msg.shape[:-1] + (n, 16, 4)).astype(jnp.uint32)
    return (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]


def words_to_bytes(w: jnp.ndarray) -> jnp.ndarray:
    """Big-endian uint32[..., 8] -> uint8[..., 32]."""
    parts = [(w >> np.uint32(s)).astype(jnp.uint8) for s in (24, 16, 8, 0)]
    return jnp.stack(parts, axis=-1).reshape(w.shape[:-1] + (32,))


def sha256(msg: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., N] (N static) -> digest uint8[..., 32]."""
    n = msg.shape[-1]
    tail = jnp.broadcast_to(jnp.asarray(pad(n)), msg.shape[:-1] + (len(pad(n)),))
    padded = jnp.concatenate([msg, tail], axis=-1)
    return words_to_bytes(sha256_blocks(bytes_to_words(padded)))
