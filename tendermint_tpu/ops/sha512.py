"""Batched SHA-512 on TPU via paired-uint32 64-bit emulation.

ed25519 needs SHA-512 for the verification challenge k = H(R || A || M)
(reference era go-crypto; reference `types/vote_set.go:175` triggers one per
vote).  TPU lanes are 32-bit, so each 64-bit word lives as a (hi, lo) uint32
pair; rotations/shifts/adds are recomposed from 32-bit ops.  Message length
is static per call site (sign-bytes are fixed-layout, see
`tendermint_tpu.types.canonical`).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

_K64 = [
    0x428a2f98d728ae22, 0x7137449123ef65cd, 0xb5c0fbcfec4d3b2f, 0xe9b5dba58189dbbc,
    0x3956c25bf348b538, 0x59f111f1b605d019, 0x923f82a4af194f9b, 0xab1c5ed5da6d8118,
    0xd807aa98a3030242, 0x12835b0145706fbe, 0x243185be4ee4b28c, 0x550c7dc3d5ffb4e2,
    0x72be5d74f27b896f, 0x80deb1fe3b1696b1, 0x9bdc06a725c71235, 0xc19bf174cf692694,
    0xe49b69c19ef14ad2, 0xefbe4786384f25e3, 0x0fc19dc68b8cd5b5, 0x240ca1cc77ac9c65,
    0x2de92c6f592b0275, 0x4a7484aa6ea6e483, 0x5cb0a9dcbd41fbd4, 0x76f988da831153b5,
    0x983e5152ee66dfab, 0xa831c66d2db43210, 0xb00327c898fb213f, 0xbf597fc7beef0ee4,
    0xc6e00bf33da88fc2, 0xd5a79147930aa725, 0x06ca6351e003826f, 0x142929670a0e6e70,
    0x27b70a8546d22ffc, 0x2e1b21385c26c926, 0x4d2c6dfc5ac42aed, 0x53380d139d95b3df,
    0x650a73548baf63de, 0x766a0abb3c77b2a8, 0x81c2c92e47edaee6, 0x92722c851482353b,
    0xa2bfe8a14cf10364, 0xa81a664bbc423001, 0xc24b8b70d0f89791, 0xc76c51a30654be30,
    0xd192e819d6ef5218, 0xd69906245565a910, 0xf40e35855771202a, 0x106aa07032bbd1b8,
    0x19a4c116b8d2d0c8, 0x1e376c085141ab53, 0x2748774cdf8eeb99, 0x34b0bcb5e19b48a8,
    0x391c0cb3c5c95a63, 0x4ed8aa4ae3418acb, 0x5b9cca4f7763e373, 0x682e6ff3d6b2b8a3,
    0x748f82ee5defb2fc, 0x78a5636f43172f60, 0x84c87814a1f0ab72, 0x8cc702081a6439ec,
    0x90befffa23631e28, 0xa4506cebde82bde9, 0xbef9a3f7b2c67915, 0xc67178f2e372532b,
    0xca273eceea26619c, 0xd186b8c721c0c207, 0xeada7dd6cde0eb1e, 0xf57d4f7fee6ed178,
    0x06f067aa72176fba, 0x0a637dc5a2c898a6, 0x113f9804bef90dae, 0x1b710b35131c471b,
    0x28db77f523047d84, 0x32caab7b40c72493, 0x3c9ebe0a15c9bebc, 0x431d67c49c100d4c,
    0x4cc5d4becb3e42b6, 0x597f299cfc657e2a, 0x5fcb6fab3ad6faec, 0x6c44198c4a475817,
]
_KHI = np.array([k >> 32 for k in _K64], dtype=np.uint32)
_KLO = np.array([k & 0xFFFFFFFF for k in _K64], dtype=np.uint32)

_H0 = [0x6a09e667f3bcc908, 0xbb67ae8584caa73b, 0x3c6ef372fe94f82b,
       0xa54ff53a5f1d36f1, 0x510e527fade682d1, 0x9b05688c2b3e6c1f,
       0x1f83d9abfb41bd6b, 0x5be0cd19137e2179]


def _add64(ah, al, bh, bl):
    lo = al + bl
    hi = ah + bh + (lo < al).astype(jnp.uint32)
    return hi, lo


def _rotr64(h, l, n):
    if n == 0:
        return h, l
    if n < 32:
        nh = (h >> np.uint32(n)) | (l << np.uint32(32 - n))
        nl = (l >> np.uint32(n)) | (h << np.uint32(32 - n))
        return nh, nl
    if n == 32:
        return l, h
    return _rotr64(l, h, n - 32)


def _shr64(h, l, n):
    assert 0 < n < 32
    return h >> np.uint32(n), (l >> np.uint32(n)) | (h << np.uint32(32 - n))


def pad(nbytes: int) -> np.ndarray:
    """Static SHA-512 padding suffix (uint8[...]): 0x80, zeros, 128-bit len."""
    padlen = (112 - (nbytes + 1)) % 128
    tail = np.zeros(1 + padlen + 16, dtype=np.uint8)
    tail[0] = 0x80
    bits = nbytes * 8
    for i in range(16):
        tail[-1 - i] = (bits >> (8 * i)) & 0xFF
    return tail


def _bytes_to_words(msg):
    """uint8[..., 128*n] -> (hi, lo) uint32[..., n, 16] big-endian."""
    n = msg.shape[-1] // 128
    b = msg.reshape(msg.shape[:-1] + (n, 16, 8)).astype(jnp.uint32)
    hi = (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]
    lo = (b[..., 4] << 24) | (b[..., 5] << 16) | (b[..., 6] << 8) | b[..., 7]
    return hi, lo


def _xor3(a, b, c):
    return a[0] ^ b[0] ^ c[0], a[1] ^ b[1] ^ c[1]


_UNROLL = 8       # rounds per scan step (see ops.sha256._compress); 8
                  # halves the body XLA compiles vs 16 with no measurable
                  # runtime cost (the 80 rounds are sequential either way)


def _compress(state, wh16, wl16):
    """One compression: lax.scan over round groups with a rolling 16-pair
    message window in the carry — same formulation as
    `ops.sha256._compress` (which documents the why), with every 64-bit
    word as a (hi, lo) uint32 pair."""
    ks = jnp.asarray(
        np.stack([_KHI.reshape(80 // _UNROLL, _UNROLL),
                  _KLO.reshape(80 // _UNROLL, _UNROLL)], axis=1))

    def step(carry, k):
        (ah, al, bh, bl, ch_, cl, dh, dl,
         eh, el, fh, fl, gh, gl, hh, hl) = carry[:16]
        wh = list(carry[16:32])
        wl = list(carry[32:48])
        for j in range(_UNROLL):
            twh, twl = wh[0], wl[0]
            a = (wh[1], wl[1])
            b = (wh[14], wl[14])
            s0 = _xor3(_rotr64(*a, 1), _rotr64(*a, 8), _shr64(*a, 7))
            s1 = _xor3(_rotr64(*b, 19), _rotr64(*b, 61), _shr64(*b, 6))
            nh, nl = _add64(wh[0], wl[0], *s0)
            nh, nl = _add64(nh, nl, wh[9], wl[9])
            nh, nl = _add64(nh, nl, *s1)
            wh = wh[1:] + [nh]
            wl = wl[1:] + [nl]
            s1 = _xor3(_rotr64(eh, el, 14), _rotr64(eh, el, 18),
                       _rotr64(eh, el, 41))
            chh = (eh & fh) ^ (~eh & gh)
            chl = (el & fl) ^ (~el & gl)
            th, tl = _add64(hh, hl, *s1)
            th, tl = _add64(th, tl, chh, chl)
            th, tl = _add64(th, tl, k[0, j], k[1, j])
            th, tl = _add64(th, tl, twh, twl)
            s0 = _xor3(_rotr64(ah, al, 28), _rotr64(ah, al, 34),
                       _rotr64(ah, al, 39))
            majh = (ah & bh) ^ (ah & ch_) ^ (bh & ch_)
            majl = (al & bl) ^ (al & cl) ^ (bl & cl)
            t2h, t2l = _add64(*s0, majh, majl)
            ndh, ndl = _add64(dh, dl, th, tl)
            nah, nal = _add64(th, tl, t2h, t2l)
            (ah, al, bh, bl, ch_, cl, dh, dl,
             eh, el, fh, fl, gh, gl, hh, hl) = (
                nah, nal, ah, al, bh, bl, ch_, cl,
                ndh, ndl, eh, el, fh, fl, gh, gl)
        st = (ah, al, bh, bl, ch_, cl, dh, dl,
              eh, el, fh, fl, gh, gl, hh, hl)
        return st + tuple(wh) + tuple(wl), None

    init = (tuple(state) + tuple(wh16[..., i] for i in range(16))
            + tuple(wl16[..., i] for i in range(16)))
    out, _ = jax.lax.scan(step, init, ks)
    res = []
    for i in range(8):
        h, l = _add64(state[2 * i], state[2 * i + 1],
                      out[2 * i], out[2 * i + 1])
        res.extend([h, l])
    return tuple(res)


def sha512(msg: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., N] (N static) -> digest uint8[..., 64]."""
    n = msg.shape[-1]
    tail = jnp.broadcast_to(jnp.asarray(pad(n)), msg.shape[:-1] + (len(pad(n)),))
    padded = jnp.concatenate([msg, tail], axis=-1)
    wh, wl = _bytes_to_words(padded)
    state = []
    for h in _H0:
        state.append(jnp.broadcast_to(jnp.uint32(h >> 32), msg.shape[:-1]))
        state.append(jnp.broadcast_to(jnp.uint32(h & 0xFFFFFFFF), msg.shape[:-1]))
    state = tuple(state)
    nblocks = wh.shape[-2]
    for i in range(nblocks):
        state = _compress(state, wh[..., i, :], wl[..., i, :])
    # big-endian digest bytes
    words = jnp.stack(state, axis=-1)  # [..., 16] hi/lo interleaved
    parts = [(words >> np.uint32(s)).astype(jnp.uint8) for s in (24, 16, 8, 0)]
    return jnp.stack(parts, axis=-1).reshape(msg.shape[:-1] + (64,))
