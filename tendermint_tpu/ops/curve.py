"""Batched edwards25519 group operations on TPU.

Points live in extended homogeneous coordinates (X, Y, Z, T) with XY = ZT —
each coordinate a radix-2^8 limb array `[..., 32]` from
`tendermint_tpu.ops.field`.  All ops broadcast over leading batch dims and
are built from static-shape primitives (lax.scan/fori_loop for ladders), so
a single jit handles any batch size without graph blowup.

This is the group layer under the batch ed25519 verifier that replaces the
reference's scalar per-vote verify (reference `types/vote_set.go:175`,
`types/validator_set.go:247-249`).  Formulas: add-2008-hwcd-3 /
dbl-2008-hwcd for a=-1 twisted Edwards, the same shapes the reference-era
Go ed25519 uses internally.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from tendermint_tpu.ops import field as fe
from tendermint_tpu.ops import scalar as sc
from tendermint_tpu.crypto import pure_ed25519 as ref

# Module-level constant limb arrays (device-cached by jit as needed).
_D2 = fe.int_to_limbs(fe.D2)
_SQRT_M1 = fe.int_to_limbs(fe.SQRT_M1)
_D = fe.int_to_limbs(fe.D)
_ONE = fe.int_to_limbs(1)
_ZERO = np.zeros(fe.NLIMBS, dtype=np.int32)


def identity(batch_shape=()) -> tuple:
    z = jnp.broadcast_to(jnp.asarray(_ZERO), batch_shape + (fe.NLIMBS,))
    o = jnp.broadcast_to(jnp.asarray(_ONE), batch_shape + (fe.NLIMBS,))
    return (z, o, o, z)


def pt_add(Q, R):
    """Complete extended addition (add-2008-hwcd-3, a=-1): 9 field muls."""
    x1, y1, z1, t1 = Q
    x2, y2, z2, t2 = R
    a = fe.mul(fe.sub(y1, x1), fe.sub(y2, x2))
    b = fe.mul(fe.add(y1, x1), fe.add(y2, x2))
    c = fe.mul(fe.mul(t1, t2), jnp.asarray(_D2))
    d = fe.mul_small(fe.mul(z1, z2), 2)
    e, f = fe.sub(b, a), fe.sub(d, c)
    g, h = fe.add(d, c), fe.add(b, a)
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def pt_add_affine(Q, aff):
    """Mixed addition with a precomputed (y+x, y-x, 2d*x*y) entry: 7 muls.

    The (1, 1, 0) entry acts as the identity, so window tables need no
    special case for digit 0.

    Operands are kept fully carried (the |limb| <= 512 invariant) between
    steps: `fe.mul`'s f32 convolution needs every column sum below 2^24,
    which the invariant guarantees (tests/test_field.py
    `test_mixed_add_interval_bounds` proves it by exact per-limb interval
    propagation).
    """
    x1, y1, z1, t1 = Q
    yplusx, yminusx, xy2d = aff
    a = fe.mul(fe.sub(y1, x1), yminusx)
    b = fe.mul(fe.add(y1, x1), yplusx)
    c = fe.mul(t1, xy2d)
    d = fe.mul_small(z1, 2)
    e, f = fe.sub(b, a), fe.sub(d, c)
    g, h = fe.add(d, c), fe.add(b, a)
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def pt_dbl(Q):
    """Dedicated doubling (dbl-2008-hwcd, a=-1): 4 sqr + 4 mul."""
    x1, y1, z1, _ = Q
    a = fe.sqr(x1)
    b = fe.sqr(y1)
    c = fe.mul_small(fe.sqr(z1), 2)
    e = fe.sub(fe.sub(fe.sqr(fe.add(x1, y1)), a), b)   # 2*x*y
    g = fe.sub(b, a)          # a*A + B with a=-1
    f = fe.sub(g, c)
    h = fe.neg(fe.add(a, b))  # a*A - B
    return (fe.mul(e, f), fe.mul(g, h), fe.mul(f, g), fe.mul(e, h))


def pt_neg(Q):
    x, y, z, t = Q
    return (fe.neg(x), y, z, fe.neg(t))


def pt_eq(Q, R) -> jnp.ndarray:
    """Projective equality mask: X1*Z2 == X2*Z1 and Y1*Z2 == Y2*Z1."""
    x1, y1, z1, _ = Q
    x2, y2, z2, _ = R
    ex = fe.eq(fe.mul(x1, z2), fe.mul(x2, z1))
    ey = fe.eq(fe.mul(y1, z2), fe.mul(y2, z1))
    return ex & ey


def pt_select(mask, Q, R):
    """Elementwise select: mask[...] ? Q : R."""
    m = mask[..., None]
    return tuple(jnp.where(m, q, r) for q, r in zip(Q, R))


def pt_on_curve(Q) -> jnp.ndarray:
    """-x^2 + y^2 == z^2 + d*t^2  and  x*y == z*t (extended-coords check)."""
    x, y, z, t = Q
    lhs = fe.sub(fe.sqr(y), fe.sqr(x))
    rhs = fe.add(fe.sqr(z), fe.mul(fe.sqr(t), jnp.asarray(_D)))
    return fe.eq(lhs, rhs) & fe.eq(fe.mul(x, y), fe.mul(z, t))


def _lt_p(b: jnp.ndarray) -> jnp.ndarray:
    """Canonical-encoding check: little-endian bytes [..., 32] < p."""
    return sc.lt_const(b, fe._P_LIMBS)


def decompress(b: jnp.ndarray) -> tuple:
    """uint8[..., 32] -> (point, ok_mask).

    Matches `crypto.pure_ed25519.pt_decode` on every input: rejects y >= p,
    non-residue x^2, and x == 0 with sign bit set.  On rejected lanes the
    returned point is garbage and must be masked by `ok`.
    """
    sign = (b[..., 31] >> 7).astype(jnp.int32)
    y_bytes = b.at[..., 31].set(b[..., 31] & 0x7F)
    ok = _lt_p(y_bytes)
    y = fe.from_bytes(y_bytes)
    y2 = fe.sqr(y)
    u = fe.sub(y2, jnp.asarray(_ONE))
    v = fe.add(fe.mul(y2, jnp.asarray(_D)), jnp.asarray(_ONE))
    v3 = fe.mul(fe.sqr(v), v)
    v7 = fe.mul(fe.sqr(v3), v)
    x = fe.mul(fe.mul(u, v3), fe.pow22523(fe.mul(u, v7)))
    vx2 = fe.mul(v, fe.sqr(x))
    root1 = fe.eq(vx2, u)
    root2 = fe.eq(vx2, fe.neg(u))
    x = jnp.where(root2[..., None], fe.mul(x, jnp.asarray(_SQRT_M1)), x)
    ok = ok & (root1 | root2)
    # x == 0 (i.e. u == 0) with sign bit set is invalid
    ok = ok & ~(fe.is_zero(u) & (sign == 1))
    flip = fe.parity(x) != sign
    x = jnp.where(flip[..., None], fe.neg(x), x)
    one = jnp.broadcast_to(jnp.asarray(_ONE), y.shape)
    return (x, y, one, fe.mul(x, y)), ok


def encode(Q) -> jnp.ndarray:
    """Point -> canonical uint8[..., 32] (y with sign-of-x top bit)."""
    x, y, z, _ = Q
    zi = fe.inv(z)
    xb = fe.parity(fe.mul(x, zi))
    yb = fe.to_bytes(fe.mul(y, zi))
    return yb.at[..., 31].set(yb[..., 31] | (xb << 7).astype(jnp.uint8))


def encode_batch(Q) -> tuple:
    """Flat-batched encode: coords [N, 32] -> (uint8[N, 32], ok[N]).

    One Montgomery batch inversion (`field.batch_inv`) replaces the
    per-lane ~265-mul inversion ladder `encode` pays — ~5 muls/lane.
    This is what lets the verifier check enc([s]B + [k](-A)) == R_bytes
    instead of decompressing R per lane (~270 muls).  ok is False where
    Z == 0 (not a projective point; garbage lanes from masked failures).
    """
    x, y, z, _ = Q
    zi, nz = fe.batch_inv(z)
    xb = fe.parity(fe.mul(x, zi))
    yb = fe.to_bytes(fe.mul(y, zi))
    return (yb.at[..., 31].set(yb[..., 31] | (xb << 7).astype(jnp.uint8)),
            nz)


# --- scalar multiplication ------------------------------------------------

def _build_window_table(Q):
    """[..., 16, 32] per coordinate: T[j] = j*Q via 15 chained adds."""
    def step(acc, _):
        nxt = pt_add(acc, Q)
        return nxt, acc
    _, rows = lax.scan(step, identity(Q[0].shape[:-1]), None, length=16)
    # rows: [16, ..., 32] per coord; move table axis next to limbs
    return tuple(jnp.moveaxis(r, 0, -2) for r in rows)


def scalar_mul(s: jnp.ndarray, Q) -> tuple:
    """[s]Q for s = little-endian bytes/limbs [..., 32]; 4-bit windows.

    256 doublings + 64 table adds + 15 setup adds, all under lax.scan so the
    traced graph stays O(one window body).
    """
    tbl = _build_window_table(Q)
    wins = sc.nibbles(s)                       # [..., 64] LSB-first
    wins_t = jnp.moveaxis(wins, -1, 0)[::-1]   # [64, ...] MSB-first

    def body(acc, w):
        acc = lax.fori_loop(0, 4, lambda _, p: pt_dbl(p), acc)
        sel = tuple(
            jnp.take_along_axis(t, w[..., None, None], axis=-2)[..., 0, :]
            for t in tbl)
        return pt_add(acc, sel), None

    acc, _ = lax.scan(body, identity(Q[0].shape[:-1]), wins_t)
    return acc


COMB_WBITS = 10                       # per-validator comb window width
COMB_WINDOWS = -(-256 // COMB_WBITS)  # 26 windows cover 256 bits
COMB_DIGITS = 1 << COMB_WBITS


def _comb_row0(Q) -> tuple:
    """Window-0 digit rows j*Q for j in [0, 1024): a 256-step add scan
    builds digits < 256, then three WIDE adds of 256Q/512Q/768Q extend to
    1024 (not a 1024-step scan).  Coords [1024, ..., V, 32] per coord."""
    def add_step(acc, _):
        nxt = pt_add(acc, Q)
        return nxt, acc
    p256, row_lo = lax.scan(add_step, identity(Q[0].shape[:-1]), None,
                            length=256)
    p256w = tuple(jnp.broadcast_to(c, q.shape)
                  for c, q in zip(p256, row_lo))

    def quarter_step(q, _):             # j0 + 256, j0 + 512, j0 + 768
        nxt = pt_add(q, p256w)
        return nxt, nxt

    _, rest = lax.scan(quarter_step, row_lo, None, length=3)
    return tuple(
        jnp.concatenate(
            [row_lo[i], rest[i].reshape((-1,) + rest[i].shape[2:])], axis=0)
        for i in range(4))


def build_affine_comb(Q) -> tuple:
    """Per-point 10-bit comb tables, built ON DEVICE as packed affine.

    Q: point with coords [..., V, 32] (V points, e.g. one per validator).
    Returns (packed uint8[26, 1024, V, 3, 32], ok bool[V]) where entry
    [w, j, v] = (y+x, y-x, 2d*x*y) of j * 2^(10w) * Q_v in canonical
    bytes — so [k]Q needs 26 gathered mixed adds (`pt_add_affine`,
    7 muls) and ZERO doublings; uint8 storage quarters the hot loop's
    gather traffic, and the (1, 1, 0) identity entries make digit 0 a
    no-op.  10-bit windows trade 4x table memory for 6 fewer adds per
    lane vs an 8-bit comb.

    Why fused: per window the extended row converts to affine bytes
    INSIDE the scan body (one Montgomery batch inversion per window), so
    only the uint8 output and one extended row ever live on device — a
    two-phase build materializes all 26 windows in int32 extended
    coordinates (~1.7 GB at V=128) plus inversion temporaries, which
    OOMs a 16 GB chip.  Sequential depth ~530 point ops; fast-sync then
    amortizes the build over thousands of commits against the same set.
    """
    def window_step(row, _):
        packed, ok = _affine_pack(row)
        # x1024 = shift one window up; fori keeps ONE doubling body in
        # the graph (10 inline copies of the 12-mul dbl were a large
        # slice of the build's 130s+ XLA compile, VERDICT r4 #3)
        nxt = lax.fori_loop(0, COMB_WBITS, lambda _, p: pt_dbl(p), row)
        return nxt, (packed, ok)

    _, (tbl, oks) = lax.scan(window_step, _comb_row0(Q), None,
                             length=COMB_WINDOWS)
    return tbl, jnp.all(oks, axis=(0, 1))


def _affine_pack(row) -> tuple:
    """One window's extended coords [1024, ..., V, 32] -> packed affine
    uint8[1024, ..., V, 3, 32] + per-entry nonzero mask.  One batch
    inversion normalizes the whole window; Z == 0 lanes (garbage chains
    from an invalid input point) are flagged False."""
    x, y, z, _ = row
    shape = z.shape
    zi, nz = fe.batch_inv(z.reshape(-1, fe.NLIMBS))
    zi = zi.reshape(shape)
    xa, ya = fe.mul(x, zi), fe.mul(y, zi)
    packed = jnp.stack([
        fe.to_bytes(fe.add(ya, xa)),
        fe.to_bytes(fe.sub(ya, xa)),
        fe.to_bytes(fe.mul(fe.mul(xa, ya), jnp.asarray(_D2))),
    ], axis=-2)
    return packed, nz.reshape(shape[:-1])


# Static layout for 10-bit digit extraction: window w covers bits
# [10w, 10w+10) — always two bytes (offset 0/2/4/6); the top window has
# only 6 real bits (masked hi byte).
_D10_LO = np.array([(COMB_WBITS * w) // 8 for w in range(COMB_WINDOWS)])
_D10_SH = np.array([(COMB_WBITS * w) % 8 for w in range(COMB_WINDOWS)])
_D10_HI = np.minimum(_D10_LO + 1, fe.NLIMBS - 1)
_D10_HI_OK = (_D10_LO + 1 <= fe.NLIMBS - 1).astype(np.int32)


def digits10(s: jnp.ndarray) -> jnp.ndarray:
    """Bytes/limbs [..., 32] -> 26 little-endian 10-bit digits [..., 26]."""
    x = s.astype(jnp.int32)
    lo = jnp.take(x, jnp.asarray(_D10_LO), axis=-1)
    hi = jnp.take(x, jnp.asarray(_D10_HI), axis=-1) * jnp.asarray(_D10_HI_OK)
    sh = jnp.asarray(_D10_SH)
    return ((lo >> sh) | (hi << (8 - sh))) & (COMB_DIGITS - 1)


def scalar_mul_comb(tbl: jnp.ndarray, val_idx: jnp.ndarray,
                    s: jnp.ndarray) -> tuple:
    """[s] * Q_{val_idx} from packed affine comb tables.

    tbl: `build_affine_comb` output uint8[26, 1024, V, 3, 32];
    val_idx int32 [N]; s bytes/limbs [N, 32] -> point coords [N, 32].
    26 gathered mixed adds, no doublings: ~182 field muls per lane vs
    ~2760 for the cold variable-base ladder in `scalar_mul`.
    """
    V = tbl.shape[2]
    digits = jnp.moveaxis(digits10(s), -1, 0)           # [26, N]

    def body(acc, xs):
        digit, tw = xs                   # tw: [1024, V, 3, 32] uint8
        flat = tw.reshape(COMB_DIGITS * V, 3, fe.NLIMBS)
        sel = jnp.take(flat, digit * V + val_idx, axis=0).astype(jnp.int32)
        aff = (sel[..., 0, :], sel[..., 1, :], sel[..., 2, :])
        return pt_add_affine(acc, aff), None

    acc, _ = lax.scan(body, identity(s.shape[:-1]), (digits, tbl))
    return acc


BASE_WBITS = 12                      # fixed-base comb window width
BASE_WINDOWS = -(-256 // BASE_WBITS)  # 22 windows cover 256 bits


@functools.lru_cache(maxsize=None)
def _base_table() -> np.ndarray:
    """np.uint8[22, 4096, 3, 32]: window w, digit j -> affine precomp of
    j * 2^(12w) * B as (y+x, y-x, 2d*x*y) canonical byte rows.

    12-bit windows (VERDICT r3 lever): 22 mixed adds per [s]B instead of
    the 8-bit comb's 32 — the ~8.6 MB table stays device-resident.  Built
    once host-side from the golden bigint reference (~90k bigint adds,
    well under a second) and lru-cached for the process.
    """
    nwin, ndig = BASE_WINDOWS, 1 << BASE_WBITS
    pts = []
    P = ref.BASE
    for w in range(nwin):
        acc = ref.IDENT
        for _ in range(ndig):
            pts.append(acc)
            acc = ref.pt_add(acc, P)
        P = acc  # acc == 2^BASE_WBITS * P == 2^(12(w+1)) * B
    # Montgomery batch inversion: one modexp for all Z coordinates.
    prefix, run = [], 1
    for p in pts:
        prefix.append(run)
        run = run * p[2] % ref.P
    run_inv = pow(run, ref.P - 2, ref.P)
    tbl = np.zeros((nwin, ndig, 3, fe.NLIMBS), dtype=np.uint8)
    for idx in range(len(pts) - 1, -1, -1):
        x, y, z, _ = pts[idx]
        zi = run_inv * prefix[idx] % ref.P
        run_inv = run_inv * z % ref.P
        xa, ya = x * zi % ref.P, y * zi % ref.P
        w, j = divmod(idx, ndig)
        tbl[w, j, 0] = fe.int_to_limbs((ya + xa) % ref.P)
        tbl[w, j, 1] = fe.int_to_limbs((ya - xa) % ref.P)
        tbl[w, j, 2] = fe.int_to_limbs(2 * fe.D * xa * ya % ref.P)
    return tbl


# Static per-window byte/shift layout for 12-bit digit extraction: window
# w covers bits [12w, 12w+12), i.e. bytes lo=3w//2 (shifted by 0 or 4)
# and lo+1; the top window only has 4 real bits (masked hi byte).
_D12_LO = np.array([(12 * w) // 8 for w in range(BASE_WINDOWS)])
_D12_ODD = np.array([(12 * w) % 8 == 4 for w in range(BASE_WINDOWS)])
_D12_HI = np.minimum(_D12_LO + 1, fe.NLIMBS - 1)
_D12_HI_OK = (_D12_LO + 1 <= fe.NLIMBS - 1).astype(np.int32)


def digits12(s: jnp.ndarray) -> jnp.ndarray:
    """Bytes/limbs [..., 32] -> 22 little-endian 12-bit digits [..., 22]."""
    x = s.astype(jnp.int32)
    lo = jnp.take(x, jnp.asarray(_D12_LO), axis=-1)
    hi = jnp.take(x, jnp.asarray(_D12_HI), axis=-1) * jnp.asarray(_D12_HI_OK)
    even = lo + ((hi & 0xF) << 8)
    odd = (lo >> 4) + (hi << 4)
    return jnp.where(jnp.asarray(_D12_ODD), odd, even)


def scalar_mul_base(s: jnp.ndarray, tbl: jnp.ndarray | None = None) -> tuple:
    """[s]B via the 12-bit fixed-base comb: 22 mixed adds, zero doublings.

    Pass the table (`_base_table()` uploaded once) as `tbl` from jitted
    entry points: baked in as a graph literal the 8.6 MB constant adds
    ~5s of XLA compile per executable (measured v5e, VERDICT r4 #3)."""
    if tbl is None:
        tbl = jnp.asarray(_base_table())       # [22, 4096, 3, 32]
    digits = jnp.moveaxis(digits12(s), -1, 0)  # [22, ...]

    def body(acc, xs):
        digit, tblw = xs
        sel = jnp.take(tblw, digit, axis=0).astype(jnp.int32)  # [..., 3, 32]
        aff = (sel[..., 0, :], sel[..., 1, :], sel[..., 2, :])
        return pt_add_affine(acc, aff), None

    acc, _ = lax.scan(body, identity(s.shape[:-1]), (digits, tbl))
    return acc


