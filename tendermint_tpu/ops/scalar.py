"""Batched arithmetic mod the ed25519 group order L on TPU.

L = 2^252 + 27742317777372353535851937790883648493.  The verifier needs two
scalar ops per signature (reference scalar path: one per vote,
`types/vote_set.go:175`):
  * `reduce512` — fold the 64-byte SHA-512 challenge H(R||A||M) to k mod L,
  * `lt_L`       — the malleability check s < L on the signature's s half.

Values are little-endian radix-2^8 limbs in int32 lanes (byte == limb), the
same representation `tendermint_tpu.ops.field` uses, so signature bytes feed
straight in.  The fold uses the signed identity 2^256 = -16c (mod L) with
c = L - 2^252: three folds take 512 bits to < 2^257, then a binary chain of
conditional subtractions {16L..L} lands in [0, L).  Everything is exact int32
with static shapes — jit/vmap friendly.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

L = 2**252 + 27742317777372353535851937790883648493
_C = L - 2**252            # 125 bits
_C16 = 16 * _C             # 129 bits -> 17 limbs


def _int_to_limbs(x: int, n: int) -> np.ndarray:
    assert 0 <= x < 1 << (8 * n)
    return np.array([(x >> (8 * i)) & 0xFF for i in range(n)], dtype=np.int32)


_C16_LIMBS = _int_to_limbs(_C16, 17)
L_LIMBS = _int_to_limbs(L, 33)
# Binary csub ladder: value < 32L after the folds, so 16L..L suffices.
_KL_LIMBS = [_int_to_limbs(k * L, 33) for k in (16, 8, 4, 2, 1)]


def _carry(x: jnp.ndarray) -> jnp.ndarray:
    """Signed exact carry: limbs -> [0,255] plus an appended top limb.

    Exact for any int32 limbs with |limb| < 2^23; only the final limb may
    be negative (it absorbs the net overflow/underflow).  Fully parallel
    (VERDICT r3): 4 shift-and-fold passes leave body limbs in [-1, 256],
    a +1-per-limb lift makes them nonnegative for the Kogge-Stone exact
    normalize, and a borrow-lookahead subtraction takes the lift back out
    — ~20 vector ops instead of an n-step sequential chain.
    """
    from tendermint_tpu.ops.field import ks_normalize, ks_sub_const

    body, top = x, jnp.zeros_like(x[..., 0])
    for _ in range(4):
        c = body >> 8
        body = (body & 0xFF).at[..., 1:].add(c[..., :-1])
        top = top + c[..., -1]
    # body in [-1, 256]: lift by +1, normalize, subtract the lift (the
    # lookahead conditions live in ONE place — field.ks_normalize /
    # ks_sub_const)
    b, t1 = ks_normalize(body + 1)
    r, t2 = ks_sub_const(b, jnp.ones_like(b))
    return jnp.concatenate([r, (top + t1 - t2)[..., None]], axis=-1)


def _mul_const(a: jnp.ndarray, const: np.ndarray) -> jnp.ndarray:
    """Schoolbook product of limb vector `a` with a small numpy constant."""
    na, nb = a.shape[-1], len(const)
    acc = jnp.zeros(a.shape[:-1] + (na + nb - 1,), dtype=jnp.int32)
    for i in range(nb):
        acc = acc.at[..., i:i + na].add(a * int(const[i]))
    return acc


def _fold(x: jnp.ndarray) -> jnp.ndarray:
    """One application of  hi*2^256 + lo  ->  lo - 16c*hi  (mod L)."""
    lo, hi = x[..., :32], x[..., 32:]
    prod = _mul_const(hi, _C16_LIMBS)
    n = max(32, prod.shape[-1])
    lo_p = jnp.pad(lo, [(0, 0)] * (lo.ndim - 1) + [(0, n - 32)])
    prod_p = jnp.pad(prod, [(0, 0)] * (prod.ndim - 1) + [(0, n - prod.shape[-1])])
    return _carry(lo_p - prod_p)


def _csub(x: jnp.ndarray, const: np.ndarray) -> jnp.ndarray:
    """x - const if that is >= 0 else x, via borrow lookahead (33 limbs)."""
    from tendermint_tpu.ops.field import ks_sub_const

    diff, borrow = ks_sub_const(x, jnp.asarray(const))
    return jnp.where((borrow == 0)[..., None], diff, x)


def reduce512(h: jnp.ndarray) -> jnp.ndarray:
    """SHA-512 digest uint8[..., 64] (little-endian) -> (h mod L) int32[..., 32]."""
    x = h.astype(jnp.int32)
    x = _fold(x)            # 49 limbs, |value| < 2^406
    x = _fold(x)            # 34 limbs, |value| < 2^260
    x = _fold(x)            # 33+1 limbs, value in (-2^134, 2^256)
    # drop known-zero top limbs down to 33, then make positive by adding L
    x = _carry(x[..., :33] + jnp.asarray(L_LIMBS))[..., :33]
    for kl in _KL_LIMBS:
        x = _csub(x, kl)
    return x[..., :32]


def lt_const(b: jnp.ndarray, const_limbs: np.ndarray) -> jnp.ndarray:
    """Little-endian bytes/limbs [..., N] < constant -> bool[...]
    (borrow lookahead: only the final borrow is needed)."""
    from tendermint_tpu.ops.field import ks_sub_const

    _, borrow = ks_sub_const(b.astype(jnp.int32), jnp.asarray(const_limbs))
    return borrow == 1


def lt_L(s: jnp.ndarray) -> jnp.ndarray:
    """Malleability check: uint8[..., 32] little-endian value < L -> bool[...]."""
    return lt_const(s, L_LIMBS[:32])


def muladd_mod_L(k: jnp.ndarray, a: jnp.ndarray,
                 r: jnp.ndarray) -> jnp.ndarray:
    """(r + k*a) mod L for little-endian limb vectors [..., 32] — the
    signing-side scalar op (RFC 8032 step 5: S = (r + k*s) mod L).

    k < L (a reduce512 output) and a < 2^255 (a clamped secret scalar),
    so the 63-limb schoolbook product plus r stays < 2^508: column sums
    are < 32*255*255 + 255 < 2^21, inside `_carry`'s exact-int32 bound,
    and the carried value fits 64 bytes — `reduce512` finishes the fold.
    """
    acc = jnp.zeros(k.shape[:-1] + (63,), dtype=jnp.int32)
    ka = k.astype(jnp.int32)
    for i in range(32):
        acc = acc.at[..., i:i + 32].add(ka * a[..., i:i + 1].astype(jnp.int32))
    acc = acc.at[..., :32].add(r.astype(jnp.int32))
    acc = jnp.pad(acc, [(0, 0)] * (acc.ndim - 1) + [(0, 1)])
    return reduce512(_carry(acc)[..., :64])


def nibbles(s: jnp.ndarray) -> jnp.ndarray:
    """Limbs/bytes [..., 32] -> 64 little-endian 4-bit windows int32[..., 64]."""
    x = s.astype(jnp.int32)
    lo = x & 0xF
    hi = (x >> 4) & 0xF
    return jnp.stack([lo, hi], axis=-1).reshape(s.shape[:-1] + (64,))


def limbs_to_int(limbs) -> int:
    arr = np.asarray(limbs)
    return sum(int(arr[..., i]) << (8 * i) for i in range(arr.shape[-1]))
