"""Batched SHA-256 Merkle tree hashing on TPU.

Computes the same roots as the host tree (`tendermint_tpu.types.merkle` —
recursive (n+1)//2 split, 0x00/0x01 domain separation; shape from reference
`types/tx.go:29-43`) for a whole batch of equal-shaped trees at once: leaf
hashing is one lockstep SHA-256 over [B, n, leaf_len] and each tree level
is one lockstep SHA-256 over gathered (left, right) pairs.

The level schedule depends only on n (static under jit); trees in a batch
share it.  Used for block data hashes and part-set roots in batched
fast-sync replay (bench configs 2-3) where the reference re-hashes
per-block on the CPU (`blockchain/reactor.go:224`).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from tendermint_tpu.ops import sha256 as s256

LEAF_PREFIX = 0x00
INNER_PREFIX = 0x01


class _Node:
    __slots__ = ("left", "right", "parent", "height")

    def __init__(self, left=None, right=None):
        self.left, self.right = left, right
        self.parent = None
        self.height = 0 if left is None else 1 + max(left.height,
                                                     right.height)
        for c in (left, right):
            if c is not None:
                c.parent = self


@functools.lru_cache(maxsize=None)
def _plan(n: int) -> tuple:
    """Level schedule for an n-leaf reference-shaped tree.

    Returns a tuple of steps; step s is (pairs, singles): pairs int32[m, 2]
    indexes the previous level's array for (left, right) children of every
    height-s node, singles int32[k] indexes nodes passing through because
    their parent combines at a later step.  The next level's array is the
    pair outputs followed by the singles, in DFS order each.
    """
    if n == 0:
        return ()

    def build(lo: int, hi: int) -> _Node:
        if hi - lo == 1:
            return _Node()
        k = (hi - lo + 1) // 2
        return _Node(build(lo, lo + k), build(lo + k, hi))

    root = build(0, n)
    # DFS order for deterministic intra-level ordering
    order: dict[_Node, int] = {}

    def dfs(node: _Node):
        order[node] = len(order)
        if node.left is not None:
            dfs(node.left)
            dfs(node.right)

    dfs(root)

    by_height: dict[int, list[_Node]] = {}
    for node in order:
        by_height.setdefault(node.height, []).append(node)
    for nodes in by_height.values():
        nodes.sort(key=order.__getitem__)

    # level 0: leaves in DFS order == leaf index order
    current = by_height[0]
    slot = {node: i for i, node in enumerate(current)}
    steps = []
    for s in range(1, root.height + 1):
        combined = by_height.get(s, [])
        pairs = np.asarray([[slot[nd.left], slot[nd.right]]
                            for nd in combined], dtype=np.int32).reshape(-1, 2)
        singles_nodes = [nd for nd in current
                         if nd.parent is not None and nd.parent.height != s]
        singles = np.asarray([slot[nd] for nd in singles_nodes],
                             dtype=np.int32)
        current = combined + singles_nodes
        slot = {node: i for i, node in enumerate(current)}
        steps.append((pairs, singles))
    assert len(current) == 1
    return tuple(steps)


def leaf_hashes(data: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., n, L] -> leaf hashes uint8[..., n, 32] (0x00-prefixed)."""
    prefix = jnp.full(data.shape[:-1] + (1,), LEAF_PREFIX, dtype=jnp.uint8)
    return s256.sha256(jnp.concatenate([prefix, data], axis=-1))


def root_from_leaf_hashes(h: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., n, 32] leaf hashes -> root uint8[..., 32]."""
    n = h.shape[-2]
    if n == 0:
        raise ValueError("empty tree has a constant root; hash host-side")
    for pairs, singles in _plan(n):
        left = jnp.take(h, jnp.asarray(pairs[:, 0]), axis=-2)
        right = jnp.take(h, jnp.asarray(pairs[:, 1]), axis=-2)
        prefix = jnp.full(left.shape[:-1] + (1,), INNER_PREFIX,
                          dtype=jnp.uint8)
        combined = s256.sha256(
            jnp.concatenate([prefix, left, right], axis=-1))
        if len(singles):
            passthrough = jnp.take(h, jnp.asarray(singles), axis=-2)
            h = jnp.concatenate([combined, passthrough], axis=-2)
        else:
            h = combined
    return h[..., 0, :]


def roots(data: jnp.ndarray) -> jnp.ndarray:
    """uint8[..., n, L] equal-length leaves -> roots uint8[..., 32]."""
    return root_from_leaf_hashes(leaf_hashes(data))


roots_jit = jax.jit(roots)
root_from_leaf_hashes_jit = jax.jit(root_from_leaf_hashes)
leaf_hashes_jit = jax.jit(leaf_hashes)
