"""Batched ed25519 signature verification on TPU — the crypto hot plane.

Replaces the reference's one-scalar-verify-per-vote
(`types/vote_set.go:175`, `types/validator_set.go:247-264`): thousands of
(message, pubkey, signature) triples are verified in one jitted call, with
the SHA-512 challenge, the mod-L reduction, both scalar multiplications and
the final point comparison all on device.

Semantics are cofactorless verification — enc([s]B - [k]A) == R — matching
`crypto.pure_ed25519.verify` (the golden reference) bit-for-bit on valid
and adversarial inputs, plus the s < L malleability check.

Messages in one batch must share a static byte length; the consensus
sign-bytes layout is fixed-width for exactly this reason
(`tendermint_tpu.types.canonical`).  Heterogeneous batches are handled by
callers bucketing per length (see `crypto.backend`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from tendermint_tpu.ops import curve
from tendermint_tpu.ops import scalar as sc
from tendermint_tpu.ops import sha512 as s512


def verify_core(pubkeys: jnp.ndarray, sigs: jnp.ndarray,
                k_scalars: jnp.ndarray) -> jnp.ndarray:
    """Verification with a precomputed challenge scalar.

    pubkeys uint8[..., 32], sigs uint8[..., 64], k int32/uint8[..., 32]
    (k = H(R||A||M) mod L) -> bool[...].
    """
    A, ok_a = curve.decompress(pubkeys)
    R, ok_r = curve.decompress(sigs[..., :32])
    s_bytes = sigs[..., 32:]
    ok_s = sc.lt_L(s_bytes)
    sB = curve.scalar_mul_base(s_bytes)
    kA = curve.scalar_mul(k_scalars, curve.pt_neg(A))
    Rprime = curve.pt_add(sB, kA)
    return ok_a & ok_r & ok_s & curve.pt_eq(Rprime, R)


def verify(pubkeys: jnp.ndarray, msgs: jnp.ndarray,
           sigs: jnp.ndarray) -> jnp.ndarray:
    """Full batched verify: uint8 pubkeys[..., 32], msgs[..., M] (M static),
    sigs[..., 64] -> bool[...]."""
    challenge = jnp.concatenate(
        [sigs[..., :32], pubkeys, msgs], axis=-1)
    k = sc.reduce512(s512.sha512(challenge))
    return verify_core(pubkeys, sigs, k)


verify_batch = jax.jit(verify)
"""jitted entry point; jax caches one executable per (batch, msg_len) shape."""


def build_neg_comb(pubkeys: jnp.ndarray) -> tuple:
    """Decompress V pubkeys and build packed affine comb tables of THEIR
    NEGATIONS (verification needs [k](-A)).
    Returns (table uint8[26, 1024, V, 3, 32], ok bool[V]).

    One device call per validator set; the tables then serve every
    subsequent verify against that set (see `crypto.backend`'s cache).
    This is the amortization the reference cannot express — its scalar
    loop re-does the full ladder per vote (`types/validator_set.go:247`).
    """
    A, ok = curve.decompress(pubkeys)
    tbl, tbl_ok = curve.build_affine_comb(curve.pt_neg(A))
    return tbl, ok & tbl_ok


build_neg_comb_jit = jax.jit(build_neg_comb)


def verify_grouped(tables: jnp.ndarray, pub_ok: jnp.ndarray,
                   val_idx: jnp.ndarray, pubkeys: jnp.ndarray,
                   msgs: jnp.ndarray, sigs: jnp.ndarray,
                   base_tbl: jnp.ndarray | None = None) -> jnp.ndarray:
    """Grouped verify: lane i checks sig[i] by validator val_idx[i] using
    cached affine comb tables — ~8x fewer field muls than `verify`:

      * no per-lane pubkey decompress (tables carry the group element),
      * no variable-base ladder (32 gathered mixed adds, ~224 muls),
      * no per-lane R decompress: the check is enc([s]B + [k](-A)) ==
        R_bytes with the encode's inversion batched over all lanes
        (`curve.encode_batch`, ~5 muls/lane).

    The byte comparison is EXACTLY the golden semantics
    (`crypto.pure_ed25519.verify`: enc([s]B - [k]A) == R): a
    non-canonical or off-curve R encoding can never equal the canonical
    encoding of an on-curve point, which is precisely when the golden
    pt_decode rejects.

    pubkeys[N, 32] are the PER-LANE keys (only for the challenge hash
    k = H(R||A||M); group math comes from the tables).
    """
    challenge = jnp.concatenate([sigs[..., :32], pubkeys, msgs], axis=-1)
    k = sc.reduce512(s512.sha512(challenge))
    s_bytes = sigs[..., 32:]
    ok_s = sc.lt_L(s_bytes)
    # [s]B and [k](-A) stay SEPARATE scans on purpose: the two comb
    # chains are independent, so the device overlaps them — a merged
    # single-accumulator scan measured ~40% slower at 64k lanes
    sB = curve.scalar_mul_base(s_bytes, base_tbl)
    kA = curve.scalar_mul_comb(tables, val_idx, k)
    enc, ok_z = curve.encode_batch(curve.pt_add(sB, kA))
    ok_r = jnp.all(enc == sigs[..., :32], axis=-1)
    return pub_ok[val_idx] & ok_s & ok_r & ok_z


verify_grouped_jit = jax.jit(verify_grouped)


def sign_grouped_templated(a_scalars: jnp.ndarray, prefixes: jnp.ndarray,
                           pubkeys: jnp.ndarray, val_idx: jnp.ndarray,
                           tmpl_idx: jnp.ndarray, templates: jnp.ndarray,
                           base_tbl: jnp.ndarray | None = None
                           ) -> jnp.ndarray:
    """Batched RFC 8032 signing against a fixed key set: lane i signs
    templates[tmpl_idx[i]] with key val_idx[i].  Returns sigs uint8[N, 64].

    The signing mirror of `verify_grouped_templated` — where the
    reference signs one vote at a time on the CPU
    (`types/priv_validator.go` SignVote -> ed25519 scalar path), this
    runs R = [r]B, k = H(R||A||M), S = (r + k*a) mod L for thousands of
    lanes in one device call (two fixed-base combs + two SHA-512 grids).
    Used for bulk fixture/testnet signing and benchable workloads;
    bit-identical to `crypto.pure_ed25519.sign` (RFC 8032 is
    deterministic, differential-tested in tests/test_ed25519.py).

    a_scalars/prefixes are the per-key halves of SHA-512(seed) (a
    clamped, prefix raw); both [V, 32] uint8, host-derived once per set.
    """
    msgs = jnp.take(templates, tmpl_idx, axis=0)            # [N, M]
    prefix = jnp.take(prefixes, val_idx, axis=0)            # [N, 32]
    A = jnp.take(pubkeys, val_idx, axis=0)                  # [N, 32]
    a = jnp.take(a_scalars, val_idx, axis=0)                # [N, 32]
    r = sc.reduce512(s512.sha512(jnp.concatenate([prefix, msgs], axis=-1)))
    R_bytes, _ = curve.encode_batch(curve.scalar_mul_base(r, base_tbl))
    k = sc.reduce512(s512.sha512(
        jnp.concatenate([R_bytes, A, msgs], axis=-1)))
    s = sc.muladd_mod_L(k, a, r)
    return jnp.concatenate(
        [R_bytes, s.astype(jnp.uint8)], axis=-1)


sign_grouped_templated_jit = jax.jit(sign_grouped_templated)


def verify_grouped_templated(tables: jnp.ndarray, pub_ok: jnp.ndarray,
                             val_pubs: jnp.ndarray, val_idx: jnp.ndarray,
                             tmpl_idx: jnp.ndarray,
                             templates: jnp.ndarray, sigs: jnp.ndarray,
                             base_tbl: jnp.ndarray | None = None
                             ) -> jnp.ndarray:
    """Grouped verify with DEVICE-side message/pubkey assembly.

    Vote sign-bytes exclude the signer, so every lane of a commit that
    votes the same block signs the IDENTICAL fixed 128-byte message
    (`types/canonical.py` layout) — a window of K blocks has ~K distinct
    messages.  The host therefore ships only templates[T, 128] plus a
    per-lane template index, and per-lane pubkeys come from the small
    [V, 32] key matrix already resident with the comb tables: per-lane
    transfer drops from 228 B (msg+pub+sig) to 72 B (sig+two indices) —
    a 3x cut in the PCIe/interconnect cost of the verification grid.
    """
    msgs = jnp.take(templates, tmpl_idx, axis=0)
    pubkeys = jnp.take(val_pubs, val_idx, axis=0)
    return verify_grouped(tables, pub_ok, val_idx, pubkeys, msgs, sigs,
                          base_tbl)


verify_grouped_templated_jit = jax.jit(verify_grouped_templated)
