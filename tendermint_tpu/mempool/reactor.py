"""Mempool reactor: transaction gossip.

Reference: `mempool/reactor.go` — channel 0x30 (`:19`); a per-peer
`broadcastTxRoutine` walks the pool and pushes txs the peer hasn't seen
(`:111+`); inbound txs go through CheckTx like any local submission.
"""

from __future__ import annotations

import threading
import time

from tendermint_tpu.p2p.peer import Peer, Reactor
from tendermint_tpu.p2p.types import ChannelDescriptor
from tendermint_tpu.types.tx import Tx
from tendermint_tpu.utils.log import get_logger

log = get_logger("mempool")

MEMPOOL_CHANNEL = 0x30
BROADCAST_SLEEP = 0.02


class MempoolReactor(Reactor):
    def __init__(self, mempool, broadcast: bool = True):
        super().__init__()
        self.mempool = mempool
        self.broadcast = broadcast
        self._peer_stops: dict[str, threading.Event] = {}
        self._lock = threading.Lock()

    def get_channels(self):
        return [ChannelDescriptor(id=MEMPOOL_CHANNEL, priority=5,
                                  send_queue_capacity=100)]

    def add_peer(self, peer: Peer) -> None:
        if not self.broadcast:
            return
        stop = threading.Event()
        with self._lock:
            self._peer_stops[peer.id] = stop
        threading.Thread(target=self._broadcast_tx_routine,
                         args=(peer, stop), daemon=True,
                         name=f"mempool-gossip-{peer.id[:8]}").start()

    def remove_peer(self, peer: Peer, reason) -> None:
        with self._lock:
            stop = self._peer_stops.pop(peer.id, None)
        if stop is not None:
            stop.set()

    def stop(self) -> None:
        with self._lock:
            for ev in self._peer_stops.values():
                ev.set()

    def receive(self, ch_id: int, peer: Peer, msg: bytes) -> None:
        """A gossiped tx enters through CheckTx exactly like RPC
        submissions (reference `:105-109`); the cache dedupes loops."""
        if not msg:
            return
        try:
            self.mempool.check_tx(msg)
        except Exception:
            log.exception("gossiped tx failed CheckTx", peer=peer.id[:8])

    def _broadcast_tx_routine(self, peer: Peer,
                              stop: threading.Event) -> None:
        """Push pool txs the peer hasn't been sent yet (reference's
        clist walk with NextWait becomes a sent-set sweep)."""
        sent: set[bytes] = set()
        while not stop.is_set():
            try:
                # height-gating (reference `:111+` waits on peer height):
                # a peer still fast-syncing (its consensus height more
                # than one block behind the pool's) would only discard
                # tx pushes — hold gossip until it is nearly caught up
                ps = peer.get("consensus")
                if ps is not None:
                    pool_h = self.mempool.height()
                    if pool_h > 0 and ps.prs.height < pool_h:
                        stop.wait(BROADCAST_SLEEP * 5)
                        continue
                txs = self.mempool.txs_after(0)
                live = set()
                pushed = False
                for tx in txs:
                    h = Tx(tx).hash
                    live.add(h)
                    if h in sent:
                        continue
                    if peer.send(MEMPOOL_CHANNEL, tx, timeout=5.0):
                        sent.add(h)
                        pushed = True
                # prune hashes no longer in the pool (committed/evicted)
                sent &= live
                if not pushed:
                    time.sleep(BROADCAST_SLEEP)
            except Exception:
                log.exception("tx broadcast failed", peer=peer.id[:8])
                time.sleep(BROADCAST_SLEEP)
