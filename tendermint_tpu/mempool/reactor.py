"""Mempool reactor: transaction gossip.

Reference: `mempool/reactor.go` — channel 0x30 (`:19`); a per-peer
`broadcastTxRoutine` walks the pool and pushes txs the peer hasn't seen
(`:111+`); inbound txs go through CheckTx like any local submission.
"""

from __future__ import annotations

import threading

from tendermint_tpu.p2p.peer import Peer, Reactor
from tendermint_tpu.p2p.types import ChannelDescriptor
from tendermint_tpu.utils.log import get_logger

log = get_logger("mempool")

MEMPOOL_CHANNEL = 0x30
BROADCAST_SLEEP = 0.1    # idle-only safety net; gossip is event-driven


class MempoolReactor(Reactor):
    def __init__(self, mempool, broadcast: bool = True):
        super().__init__()
        self.mempool = mempool
        self.broadcast = broadcast
        self._peer_stops: dict[str, threading.Event] = {}
        self._lock = threading.Lock()
        # event-driven gossip (same shape as the consensus reactor): a
        # new local/gossiped tx bumps the sequence and wakes the
        # broadcast routines; idle routines block instead of busy-polling
        self._wake = threading.Condition()
        self._wake_seq = 0
        if hasattr(mempool, "add_notify_cb"):
            mempool.add_notify_cb(self._notify_work)

    def _notify_work(self) -> None:
        with self._wake:
            self._wake_seq += 1
            self._wake.notify_all()

    def wake(self) -> None:
        """Cross-reactor nudge: the consensus reactor calls this when a
        peer's advertised height advances, so height-gated txs retry
        immediately instead of waiting out BROADCAST_SLEEP (the safety
        net would mask the coupling if the sleep were ever raised)."""
        self._notify_work()

    def _wait_work(self, seen_seq: int, timeout: float) -> None:
        with self._wake:
            if self._wake_seq == seen_seq:
                self._wake.wait(timeout)

    def get_channels(self):
        return [ChannelDescriptor(id=MEMPOOL_CHANNEL, priority=5,
                                  send_queue_capacity=100)]

    def add_peer(self, peer: Peer) -> None:
        if not self.broadcast:
            return
        stop = threading.Event()
        with self._lock:
            self._peer_stops[peer.id] = stop
        threading.Thread(target=self._broadcast_tx_routine,
                         args=(peer, stop), daemon=True,
                         name=f"mempool-gossip-{peer.id[:8]}").start()

    def remove_peer(self, peer: Peer, reason) -> None:
        with self._lock:
            stop = self._peer_stops.pop(peer.id, None)
        if stop is not None:
            stop.set()
        self._notify_work()

    def stop(self) -> None:
        with self._lock:
            for ev in self._peer_stops.values():
                ev.set()
        if hasattr(self.mempool, "remove_notify_cb"):
            self.mempool.remove_notify_cb(self._notify_work)
        self._notify_work()

    def receive(self, ch_id: int, peer: Peer, msg: bytes) -> None:
        """A gossiped tx enters through CheckTx exactly like RPC
        submissions (reference `:105-109`); the cache dedupes loops."""
        if not msg:
            return
        try:
            self.mempool.check_tx(msg)
        except Exception:
            log.exception("gossiped tx failed CheckTx", peer=peer.id[:8])

    def _broadcast_tx_routine(self, peer: Peer,
                              stop: threading.Event) -> None:
        """Push pool txs the peer hasn't been sent yet (reference's
        clist walk with NextWait becomes a sent-set sweep)."""
        sent: set[bytes] = set()
        while not stop.is_set():
            try:
                seq = self._wake_seq
                # height-gating (reference `:111+` waits on peer height,
                # PER TX against its admission height): a peer still
                # fast-syncing would only discard pushes of txs admitted
                # far ahead of it — but gating on the pool's moving
                # height would starve old txs whenever the peer's
                # advertised height lags a block, so the reference allows
                # one-behind per tx
                ps = peer.get("consensus")
                peer_h = ps.prs.height if ps is not None else None
                pairs = self.mempool.txs_with_heights()
                live = set()
                pushed = False
                for h, tx, admit_h in pairs:
                    live.add(h)
                    if h in sent:
                        continue
                    if peer_h is not None and peer_h < admit_h - 1:
                        continue     # peer too far behind for this tx
                    if peer.send(MEMPOOL_CHANNEL, tx, timeout=5.0):
                        sent.add(h)
                        pushed = True
                # prune hashes no longer in the pool (committed/evicted)
                sent &= live
                if not pushed:
                    self._wait_work(seq, BROADCAST_SLEEP)
            except Exception:
                log.exception("tx broadcast failed", peer=peer.id[:8])
                stop.wait(BROADCAST_SLEEP)
