"""Ordered transaction pool gated by app CheckTx, behind an admission
controller that survives ingress overload.

Reference: `mempool/mempool.go` — txs enter via CheckTx on the dedicated
mempool ABCI conn (`:166-205`), LRU dedup cache of 100k (`:51,410-469`),
`Reap` for proposals (`:298-324`), post-commit `Update` + recheck pipeline
(`:329-391`), `TxsAvailable` height-gated notification (`:99-104,277-294`),
and the lock consensus holds across app Commit (`state/execution.go:248`).

The reference's concurrent linked list (tmlibs/clist) becomes an ordered
dict under one re-entrant lock: iteration order == insertion order, O(1)
removal on update, safe concurrent CheckTx from RPC threads.

Admission control (ROADMAP item 3, the "millions of users" front door):

- hard caps on resident txs (`mempool.max_txs`) and bytes
  (`mempool.max_bytes`); at the cap a new tx is admitted only by
  evicting strictly lower-priority txs, else rejected with the typed
  `ERR_MEMPOOL_FULL` result (surfaced verbatim through the RPC
  `broadcast_tx_*` paths)
- reject-before-verify backpressure: while the batch plane's mempool
  class queues more than `mempool.backpressure_lanes` pending lanes,
  enveloped txs are refused BEFORE their signature is scheduled, so a
  flood sheds at the front door instead of starving consensus lanes
- priority eviction: the envelope carries an authenticated fee/priority
  byte; victims are chosen lowest-priority-oldest first and their
  hashes leave the dedup cache, so a legitimately evicted tx can be
  resubmitted once load drops
- zero silent drops: every submission lands in exactly one outcome —
  the pool, or `mempool_rejected{reason}` — and every eviction in
  `mempool_evicted{reason}`; `mempool_admit_seconds` histograms the
  admission latency the mempool-flood scenario budgets at p50/p99.
"""

from __future__ import annotations

import hashlib
import os
import time
from collections import OrderedDict

from tendermint_tpu.abci.types import (ERR_BAD_SIG, ERR_ENCODING,
                                       ERR_MEMPOOL_FULL, Result)
from tendermint_tpu.types import merkle
from tendermint_tpu.types.tx import Tx
from tendermint_tpu.utils import lockwitness
from tendermint_tpu.utils.chaos import DeviceFault
from tendermint_tpu.utils.metrics import REGISTRY

# -- signed-tx envelope ----------------------------------------------------
# Optional authenticated tx framing: a tagged prefix carries a fee/priority
# byte, the sender's key and a signature over sha256(priority || payload),
# so the pool can reject forged submissions BEFORE the app sees them — on
# the device batch plane, where concurrent RPC CheckTx lanes coalesce into
# one verify batch.  The signature covers the DIGEST (fixed 32-byte
# message) so every lane shares one compiled shape regardless of payload
# size, and covers the priority byte so a relay cannot bump or slash a
# tx's eviction rank in flight.  Unprefixed txs skip the check entirely
# (the app's own CheckTx still runs) and rank at priority 0.
TAG_ED25519 = 0xE1      # [tag][prio 1][pub 32][sig 64][payload...]
TAG_SECP256K1 = 0xE2    # [tag][prio 1][pub 33][siglen 1][sig][payload...]


def _priority_digest(priority: int, payload: bytes) -> bytes:
    if not 0 <= priority <= 255:
        raise ValueError(f"tx priority {priority} outside 0..255")
    return hashlib.sha256(bytes([priority]) + payload).digest()


def sign_tx_ed25519(seed: bytes, payload: bytes,
                    priority: int = 0) -> bytes:
    """Wrap payload in the ed25519 envelope (test/fixture helper)."""
    from tendermint_tpu.types.keys import PrivKey
    priv = PrivKey(seed)
    digest = _priority_digest(priority, payload)
    return (bytes([TAG_ED25519, priority]) + priv.pub_key.bytes_ +
            priv.sign(digest) + payload)


def sign_tx_secp256k1(priv, payload: bytes, priority: int = 0) -> bytes:
    """Wrap payload in the secp256k1 envelope (`PrivKeySecp256k1`)."""
    digest = _priority_digest(priority, payload)
    sig = priv.sign(digest)
    return (bytes([TAG_SECP256K1, priority]) + priv.pub_key.bytes_ +
            bytes([len(sig)]) + sig + payload)


def parse_signed_tx(tx: bytes):
    """(scheme, pub, sig, payload, priority) for enveloped txs, None
    for unsigned.

    Raises ValueError on a malformed envelope: a tx claiming a signature
    scheme must never fall through as unsigned."""
    if not tx or tx[0] not in (TAG_ED25519, TAG_SECP256K1):
        return None
    if tx[0] == TAG_ED25519:
        if len(tx) < 2 + 32 + 64 + 1:
            raise ValueError("ed25519 envelope truncated")
        return ("ed25519", tx[2:34], tx[34:98], tx[98:], tx[1])
    if len(tx) < 2 + 33 + 1 + 1 + 1:
        raise ValueError("secp256k1 envelope truncated")
    siglen = tx[35]
    if siglen == 0 or len(tx) < 2 + 33 + 1 + siglen + 1:
        raise ValueError("secp256k1 envelope truncated")
    return ("secp256k1", tx[2:35], tx[36:36 + siglen],
            tx[36 + siglen:], tx[1])


def tx_priority(tx: bytes) -> int:
    """Fee/priority byte of an enveloped tx; unsigned txs rank 0."""
    parsed = parse_signed_tx(tx)
    return 0 if parsed is None else parsed[4]


# shared rejection Results: at flood rates these fire 100k+/s, and the
# dataclass construction is a measurable slice of the shed budget —
# callers treat Results as read-only
_RES_FULL = Result(code=ERR_MEMPOOL_FULL, log="mempool is full")
_RES_BACKPRESSURE = Result(
    code=ERR_MEMPOOL_FULL,
    log="mempool backpressure: verify plane saturated")


class Mempool:
    def __init__(self, proxy_mempool_conn, config=None, wal_path: str = ""):
        self.proxy = proxy_mempool_conn
        cache_size = config.cache_size if config else 100_000
        self.recheck_enabled = config.recheck if config else True
        # admission caps (getattr: a pre-admission MempoolConfig or a
        # bare stub still constructs a working pool on the defaults)
        self.max_txs = getattr(config, "max_txs", 5_000)
        self.max_bytes = getattr(config, "max_bytes", 1_073_741_824)
        self.backpressure_lanes = getattr(config, "backpressure_lanes",
                                          4_096)
        self._txs: OrderedDict[bytes, bytes] = OrderedDict()  # hash -> tx
        self._cache: OrderedDict[bytes, None] = OrderedDict()
        self._cache_size = cache_size
        self._lock = lockwitness.new_lock("mempool.lock")
        self._height = 0
        self._notified_available = False
        self._txs_available_cb = None
        self._wal_path = wal_path
        self._wal = open(wal_path, "ab") if wal_path else None
        self._recovering = False
        self._notify_cbs: list = []   # gossip wakeups on pool change
        self._tx_heights: dict[bytes, int] = {}   # hash -> admission height
        self._tx_prio: dict[bytes, int] = {}      # hash -> priority byte
        self._bytes = 0                           # resident tx bytes
        # cached min priority over the pool: the O(1) shortcut that lets
        # a full pool shed can't-possibly-fit floods without the O(n)
        # victim scan; recomputed lazily after the floor tx leaves
        self._prio_floor = 0
        self._floor_dirty = True
        # observation hook for eviction audits (eviction-storm records
        # (hash, tx, priority) of every victim); fired under the lock
        self.on_evict = None
        # pre-bound metric cells: CounterVec.labels() takes a lock per
        # call, and the flood-shed path pays it on every rejection
        self._rejected = {r: REGISTRY.mempool_rejected.labels(r)
                          for r in ("encoding", "dup", "full",
                                    "backpressure", "bad_sig", "app")}
        self._evicted_prio = REGISTRY.mempool_evicted.labels("priority")

    def add_notify_cb(self, cb) -> None:
        """Register a zero-arg callback fired whenever the pool gains a
        tx (event-driven gossip instead of polling)."""
        self._notify_cbs.append(cb)

    def remove_notify_cb(self, cb) -> None:
        """Deregister (reactor shutdown must not leak dead callbacks)."""
        try:
            self._notify_cbs.remove(cb)
        except ValueError:
            pass

    def _fire_notify(self) -> None:
        for cb in self._notify_cbs:
            try:
                cb()
            except Exception:
                pass

    # -- locking across app Commit (reference state/execution.go:248) ----
    def lock(self):
        self._lock.acquire()

    def unlock(self):
        self._lock.release()

    # -- ingestion -------------------------------------------------------
    def check_tx(self, tx: bytes, tx_hash: bytes | None = None):
        """Admit via the admission controller + app CheckTx; returns the
        Result or None when the tx is a cache duplicate (reference
        `:166-205`).  Every submission is timed into
        `mempool_admit_seconds` and lands in exactly one outcome.
        `tx_hash`, when the caller already computed it (the RPC
        broadcast handlers hash every tx for their response), skips the
        second leaf-hash — at flood rates the duplicate sha256 is a
        measurable slice of the admission budget."""
        t0 = time.perf_counter()
        try:
            return self._admit(tx, tx_hash if tx_hash is not None
                               else merkle.leaf_hash(tx))
        finally:
            REGISTRY.mempool_admit_seconds.observe(
                time.perf_counter() - t0)

    def _admit(self, tx: bytes, h: bytes):
        """The admission pipeline, cheapest gate first:

        envelope parse (priority) -> dedup cache -> backpressure
        (reject-before-verify) -> capacity/evictability -> signature
        verify (batch plane) -> app CheckTx -> evict + insert.

        The app call happens UNDER the mempool lock: consensus holds
        this lock across app Commit + update (reference proxyMtx
        semantics), so no tx can validate against a half-committed app
        and then slip into the pool after the recheck pass.  The
        signed-envelope verify runs OUTSIDE the lock (it is app-state
        independent) so concurrent RPC CheckTx lanes coalesce on the
        device batch plane instead of serializing a device round-trip
        each behind the pool lock.  Unsigned txs skip the verify legs
        entirely and resolve in ONE lock section — the flood-shed path
        a saturated pool serves at 100k+/s."""
        try:
            parsed = parse_signed_tx(tx)
        except ValueError as e:
            # malformed envelopes never enter the dedup cache: nothing
            # to uncache, and a resubmission re-parses to the same error
            self._rejected["encoding"].inc()
            return Result(code=ERR_ENCODING,
                          log=f"bad signed-tx envelope: {e}")
        prio = parsed[4] if parsed is not None else 0
        if parsed is not None:
            with self._lock:
                if not self._cache_admit_locked(h):
                    return None
            if self._backpressured():
                # reject BEFORE scheduling the verify: a signature flood
                # must not grow the plane's mempool queue unboundedly
                return self._reject(h, "backpressure", _RES_BACKPRESSURE)
            with self._lock:
                if self._find_victims_locked(len(tx), prio) is None:
                    # full and nothing strictly lower-priority to evict:
                    # reject before paying for the signature verify
                    return self._reject(h, "full", _RES_FULL)
            rej = self._verify_signed(parsed)
            if rej is not None:
                reason = ("bad_sig" if rej.code == ERR_BAD_SIG
                          else "encoding")
                return self._reject(h, reason, rej)
        with self._lock:
            if parsed is None and not self._cache_admit_locked(h):
                return None
            # capacity may have shifted while the verify ran off-lock:
            # re-pick victims under the lock that admits
            victims = self._find_victims_locked(len(tx), prio)
            if victims is None:
                # inline uncache+count (no _reject re-lock): this is
                # the bulk flood-shed exit, one lock section end to end
                self._cache.pop(h, None)
                self._rejected["full"].inc()
                return _RES_FULL
            res = self.proxy.check_tx(tx)
            if res.is_ok:
                for v in victims:
                    self._evict_locked(v)
                if victims:
                    # journal == surviving pool: a crash after the
                    # eviction must not resurrect the victims
                    self._rewrite_wal()
                if self._wal is not None and not self._recovering:
                    self._wal.write(len(tx).to_bytes(4, "big") + tx)
                    self._wal.flush()
                self._txs[h] = tx
                # reference memTx.Height: the height the tx was validated
                # at — the gossip height-gate keys on THIS, not the pool's
                # moving height (old txs must not be re-gated forever)
                self._tx_heights[h] = self._height + 1
                self._tx_prio[h] = prio
                self._bytes += len(tx)
                if not self._floor_dirty and prio < self._prio_floor:
                    self._prio_floor = prio
                self._set_gauges_locked()
                self._notify_available()
                self._fire_notify()
            else:
                # invalid tx: allow future resubmission (reference :259-264)
                self._cache.pop(h, None)
                self._rejected["app"].inc()
        return res

    def _cache_admit_locked(self, h: bytes) -> bool:
        """Claim `h` in the dedup cache; False (+ counted rejection)
        when it is already there."""
        if h in self._cache:
            self._rejected["dup"].inc()
            return False
        self._cache[h] = None
        while len(self._cache) > self._cache_size:
            self._cache.popitem(last=False)
        return True

    def _reject(self, h: bytes, reason: str, res: Result) -> Result:
        """Uncache + count: a rejected tx is never silently dropped and
        never permanently deduped — a client may resubmit once load
        drops (or with the signature fixed)."""
        with self._lock:
            self._cache.pop(h, None)
        self._rejected[reason].inc()
        return res

    # -- admission control ----------------------------------------------
    def _backpressured(self) -> bool:
        if self.backpressure_lanes <= 0:
            return False
        from tendermint_tpu import batchplane
        if not batchplane.enabled():
            return False
        return (batchplane.get_plane().class_depth(
            batchplane.CLASS_MEMPOOL) >= self.backpressure_lanes)

    def _prio_floor_locked(self) -> int:
        with self._lock:         # re-entrant; callers already hold it
            if self._floor_dirty:
                self._prio_floor = min(self._tx_prio.values(), default=0)
                self._floor_dirty = False
            return self._prio_floor

    def _find_victims_locked(self, nbytes: int, prio: int):
        """Eviction plan admitting a `prio` tx of `nbytes`: [] when it
        fits outright, the lowest-priority-oldest victim hashes when
        evicting strictly lower-priority txs makes room, None when the
        tx must be rejected (nothing evictable outranks it).  Priority
        inversion is impossible by construction: victims are consumed
        in (priority, insertion-order) order and only while < prio."""
        slots_full = (self.max_txs > 0
                      and len(self._txs) + 1 > self.max_txs)
        bytes_full = (self.max_bytes > 0
                      and self._bytes + nbytes > self.max_bytes)
        if not (slots_full or bytes_full):
            return []
        if prio <= self._prio_floor_locked():
            return None          # O(1) shed: nothing in the pool ranks lower
        victims: list[bytes] = []
        vbytes = 0
        candidates = sorted(
            ((self._tx_prio.get(hh, 0), i, hh)
             for i, hh in enumerate(self._txs)),
            key=lambda t: (t[0], t[1]))
        for p, _, hh in candidates:
            if p >= prio:
                break
            victims.append(hh)
            vbytes += len(self._txs[hh])
            slots_ok = (self.max_txs <= 0 or
                        len(self._txs) - len(victims) + 1 <= self.max_txs)
            bytes_ok = (self.max_bytes <= 0 or
                        self._bytes - vbytes + nbytes <= self.max_bytes)
            if slots_ok and bytes_ok:
                return victims
        return None

    def _evict_locked(self, h: bytes) -> None:
        tx = self._txs.pop(h)
        self._bytes -= len(tx)
        p = self._tx_prio.pop(h, 0)
        if p <= self._prio_floor:
            self._floor_dirty = True
        self._tx_heights.pop(h, None)
        # evicted != committed: the dedup cache entry goes too, so a
        # legitimate sender can resubmit once there is room
        self._cache.pop(h, None)
        self._evicted_prio.inc()
        if self.on_evict is not None:
            try:
                self.on_evict(h, tx, p)
            except Exception:
                pass

    def _set_gauges_locked(self) -> None:
        REGISTRY.mempool_size.set(len(self._txs))
        REGISTRY.mempool_bytes.set(self._bytes)

    def _verify_signed(self, parsed):
        """Envelope signature gate: None when tx may proceed to the app,
        else the rejecting `Result`.  ed25519 lanes ride the batch plane
        (mempool class — preempted by consensus votes); a `DeviceFault`
        that survives the supervised ladder falls back to the scalar
        verifier rather than rejecting a possibly-valid tx."""
        if parsed is None:
            return None
        scheme, pub, sig, payload, prio = parsed
        digest = _priority_digest(prio, payload)
        from tendermint_tpu import batchplane
        if scheme == "secp256k1":
            from tendermint_tpu.crypto import secp256k1
            if not secp256k1.AVAILABLE:
                return Result(code=ERR_ENCODING,
                              log="secp256k1 support unavailable")
            ok = bool(batchplane.verify_secp(
                [(pub, digest, sig)], producer="mempool",
                klass=batchplane.CLASS_MEMPOOL)[0])
        else:
            import numpy as np
            try:
                ok = bool(batchplane.verify_batch(
                    np.frombuffer(pub, np.uint8).reshape(1, 32),
                    np.frombuffer(digest, np.uint8).reshape(1, 32),
                    np.frombuffer(sig, np.uint8).reshape(1, 64),
                    producer="mempool",
                    klass=batchplane.CLASS_MEMPOOL)[0])
            except DeviceFault:
                from tendermint_tpu.types.keys import _verify_memo
                ok = _verify_memo(pub, digest, sig)
        if not ok:
            return Result(code=ERR_BAD_SIG,
                          log=f"invalid {scheme} tx signature")
        return None

    def _notify_available(self):
        if (self._txs_available_cb is not None and
                not self._notified_available and self._txs):
            self._notified_available = True
            self._txs_available_cb(self._height + 1)

    def set_txs_available_callback(self, cb):
        """Height-gated fire-once-per-height notification
        (reference `:99-104,277-294`)."""
        self._txs_available_cb = cb

    # -- WAL recovery (SURVEY §5 checkpoint layer 5) ----------------------
    def recover_wal(self, committed=None) -> int:
        """Re-admit journalled txs after a crash (call once at boot, after
        the app handshake restored app state).  Entries are re-run through
        CheckTx; `committed` (tx_bytes -> bool), when given, drops journal
        entries already committed to a block (e.g. via the tx index) so a
        crash between block commit and journal compaction does not re-admit
        them — apps whose CheckTx accepts anything (kvstore) would
        otherwise see at-least-once redelivery.  Without `committed` the
        contract IS at-least-once: the app's CheckTx must reject replays
        of committed txs.  A torn tail is truncated.  Returns the number
        of txs re-admitted."""
        if not self._wal_path:
            return 0
        try:
            with open(self._wal_path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return 0
        txs, off = [], 0
        while off + 4 <= len(data):
            n = int.from_bytes(data[off:off + 4], "big")
            if off + 4 + n > len(data):
                break                      # torn tail from a mid-write crash
            txs.append(data[off + 4:off + 4 + n])
            off += 4 + n
        readmitted = 0
        self._recovering = True
        try:
            for tx in txs:
                if committed is not None and committed(tx):
                    with self._lock:
                        # permanently dedupe, like update(): a peer
                        # gossiping or a client rebroadcasting this tx
                        # after the restart must not re-admit it either
                        self._cache[Tx(tx).hash] = None
                    continue
                res = self.check_tx(tx)
                if res is not None and res.is_ok:
                    readmitted += 1
        finally:
            self._recovering = False
        with self._lock:
            self._rewrite_wal()
        return readmitted

    # -- queries ---------------------------------------------------------
    def size(self) -> int:
        with self._lock:
            return len(self._txs)

    def size_bytes(self) -> int:
        """Resident tx bytes (the max_bytes cap's numerator)."""
        with self._lock:
            return self._bytes

    def height(self) -> int:
        """Last committed height this pool was updated to (gossip gate)."""
        return self._height

    def reap(self, max_txs: int) -> list[bytes]:
        """First N txs in order for a proposal (reference `:298-324`)."""
        with self._lock:
            out = []
            for tx in self._txs.values():
                if 0 <= max_txs <= len(out):
                    break
                out.append(tx)
            return out

    def txs_after(self, n: int) -> list[bytes]:
        """Gossip helper: txs from position n onward."""
        with self._lock:
            return list(self._txs.values())[n:]

    def txs_with_heights(self) -> list[tuple[bytes, bytes, int]]:
        """Gossip helper: (hash, tx, admission height) triples in pool
        order — the hash rides along so broadcast sweeps need not
        recompute it per tx per peer."""
        with self._lock:
            return [(h, tx, self._tx_heights.get(h, 0))
                    for h, tx in self._txs.items()]

    # -- post-commit -----------------------------------------------------
    def update(self, height: int, committed_txs: list[bytes]) -> None:
        """Drop committed txs, recheck the rest (reference `:329-391`).
        Caller (apply_block) already holds the lock; _lock is an RLock,
        so taking it again here is free — and keeps the pool consistent
        if update is ever reached without the outer lock()."""
        with self._lock:
            self._height = height
            self._notified_available = False
            for tx in committed_txs:
                h = Tx(tx).hash
                if self._txs.pop(h, None) is not None:
                    self._bytes -= len(tx)
                self._tx_heights.pop(h, None)
                self._tx_prio.pop(h, None)
                self._cache[h] = None   # committed: permanently deduped
            if self.recheck_enabled and self._txs:
                survivors = OrderedDict()
                for h, tx in self._txs.items():
                    if self.proxy.check_tx(tx).is_ok:
                        survivors[h] = tx
                    else:
                        self._tx_heights.pop(h, None)
                        self._tx_prio.pop(h, None)
                        self._bytes -= len(tx)
                self._txs = survivors
            self._floor_dirty = True
            self._set_gauges_locked()
            # compact the journal to the surviving pool: committed txs
            # must not be re-admitted (re-EXECUTED) by recover_wal
            self._rewrite_wal()
            if self._txs:
                self._notify_available()

    def _rewrite_wal(self) -> None:
        """Atomically rewrite the journal to exactly the current pool
        (temp + rename: a crash mid-rewrite leaves the old journal, whose
        extra entries are merely re-checked, never the empty file a
        truncate-in-place would)."""
        if not self._wal_path:
            return
        if self._wal is not None:
            self._wal.close()
        tmp = self._wal_path + ".tmp"
        with open(tmp, "wb") as f:
            for tx in self._txs.values():
                f.write(len(tx).to_bytes(4, "big") + tx)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._wal_path)
        self._wal = open(self._wal_path, "ab")

    def flush(self) -> None:
        with self._lock:
            self._txs.clear()
            self._tx_heights.clear()
            self._tx_prio.clear()
            self._cache.clear()
            self._bytes = 0
            self._floor_dirty = True
            self._set_gauges_locked()
            self._rewrite_wal()   # journal == pool, or recovery resurrects

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
