"""Ordered transaction pool gated by app CheckTx.

Reference: `mempool/mempool.go` — txs enter via CheckTx on the dedicated
mempool ABCI conn (`:166-205`), LRU dedup cache of 100k (`:51,410-469`),
`Reap` for proposals (`:298-324`), post-commit `Update` + recheck pipeline
(`:329-391`), `TxsAvailable` height-gated notification (`:99-104,277-294`),
and the lock consensus holds across app Commit (`state/execution.go:248`).

The reference's concurrent linked list (tmlibs/clist) becomes an ordered
dict under one re-entrant lock: iteration order == insertion order, O(1)
removal on update, safe concurrent CheckTx from RPC threads.
"""

from __future__ import annotations

import hashlib
import os
from collections import OrderedDict

from tendermint_tpu.abci.types import ERR_BAD_SIG, ERR_ENCODING, Result
from tendermint_tpu.types.tx import Tx
from tendermint_tpu.utils import lockwitness
from tendermint_tpu.utils.chaos import DeviceFault

# -- signed-tx envelope ----------------------------------------------------
# Optional authenticated tx framing: a tagged prefix carries the sender's
# key and a signature over sha256(payload), so the pool can reject forged
# submissions BEFORE the app sees them — on the device batch plane, where
# concurrent RPC CheckTx lanes coalesce into one verify batch.  The
# signature covers the payload DIGEST (fixed 32-byte message) so every
# lane shares one compiled shape regardless of payload size.  Unprefixed
# txs skip the check entirely (the app's own CheckTx still runs).
TAG_ED25519 = 0xE1      # [tag][pub 32][sig 64][payload...]
TAG_SECP256K1 = 0xE2    # [tag][pub 33][siglen 1][sig][payload...]


def sign_tx_ed25519(seed: bytes, payload: bytes) -> bytes:
    """Wrap payload in the ed25519 envelope (test/fixture helper)."""
    from tendermint_tpu.types.keys import PrivKey
    priv = PrivKey(seed)
    digest = hashlib.sha256(payload).digest()
    return (bytes([TAG_ED25519]) + priv.pub_key.bytes_ +
            priv.sign(digest) + payload)


def sign_tx_secp256k1(priv, payload: bytes) -> bytes:
    """Wrap payload in the secp256k1 envelope (`PrivKeySecp256k1`)."""
    digest = hashlib.sha256(payload).digest()
    sig = priv.sign(digest)
    return (bytes([TAG_SECP256K1]) + priv.pub_key.bytes_ +
            bytes([len(sig)]) + sig + payload)


def parse_signed_tx(tx: bytes):
    """(scheme, pub, sig, payload) for enveloped txs, None for unsigned.

    Raises ValueError on a malformed envelope: a tx claiming a signature
    scheme must never fall through as unsigned."""
    if not tx or tx[0] not in (TAG_ED25519, TAG_SECP256K1):
        return None
    if tx[0] == TAG_ED25519:
        if len(tx) < 1 + 32 + 64 + 1:
            raise ValueError("ed25519 envelope truncated")
        return ("ed25519", tx[1:33], tx[33:97], tx[97:])
    if len(tx) < 1 + 33 + 1 + 1:
        raise ValueError("secp256k1 envelope truncated")
    siglen = tx[34]
    if siglen == 0 or len(tx) < 1 + 33 + 1 + siglen + 1:
        raise ValueError("secp256k1 envelope truncated")
    return ("secp256k1", tx[1:34], tx[35:35 + siglen], tx[35 + siglen:])


class Mempool:
    def __init__(self, proxy_mempool_conn, config=None, wal_path: str = ""):
        self.proxy = proxy_mempool_conn
        cache_size = config.cache_size if config else 100_000
        self.recheck_enabled = config.recheck if config else True
        self._txs: OrderedDict[bytes, bytes] = OrderedDict()  # hash -> tx
        self._cache: OrderedDict[bytes, None] = OrderedDict()
        self._cache_size = cache_size
        self._lock = lockwitness.new_lock("mempool.lock")
        self._height = 0
        self._notified_available = False
        self._txs_available_cb = None
        self._wal_path = wal_path
        self._wal = open(wal_path, "ab") if wal_path else None
        self._recovering = False
        self._notify_cbs: list = []   # gossip wakeups on pool change
        self._tx_heights: dict[bytes, int] = {}   # hash -> admission height

    def add_notify_cb(self, cb) -> None:
        """Register a zero-arg callback fired whenever the pool gains a
        tx (event-driven gossip instead of polling)."""
        self._notify_cbs.append(cb)

    def remove_notify_cb(self, cb) -> None:
        """Deregister (reactor shutdown must not leak dead callbacks)."""
        try:
            self._notify_cbs.remove(cb)
        except ValueError:
            pass

    def _fire_notify(self) -> None:
        for cb in self._notify_cbs:
            try:
                cb()
            except Exception:
                pass

    # -- locking across app Commit (reference state/execution.go:248) ----
    def lock(self):
        self._lock.acquire()

    def unlock(self):
        self._lock.release()

    # -- ingestion -------------------------------------------------------
    def check_tx(self, tx: bytes):
        """Admit via app CheckTx; returns the app Result or None when the
        tx is a cache duplicate (reference `:166-205`).

        The app call happens UNDER the mempool lock: consensus holds this
        lock across app Commit + update (reference proxyMtx semantics), so
        no tx can validate against a half-committed app and then slip into
        the pool after the recheck pass.  The signed-envelope verify runs
        OUTSIDE the lock (it is app-state independent) so concurrent RPC
        CheckTx lanes coalesce on the device batch plane instead of
        serializing a device round-trip each behind the pool lock.
        """
        h = Tx(tx).hash
        with self._lock:
            if h in self._cache:
                return None
            self._cache[h] = None
            while len(self._cache) > self._cache_size:
                self._cache.popitem(last=False)
        rej = self._verify_signed(tx)
        if rej is not None:
            with self._lock:
                # bad signature: allow future resubmission of a fixed tx
                self._cache.pop(h, None)
            return rej
        with self._lock:
            res = self.proxy.check_tx(tx)
            if res.is_ok:
                if self._wal is not None and not self._recovering:
                    self._wal.write(len(tx).to_bytes(4, "big") + tx)
                    self._wal.flush()
                self._txs[h] = tx
                # reference memTx.Height: the height the tx was validated
                # at — the gossip height-gate keys on THIS, not the pool's
                # moving height (old txs must not be re-gated forever)
                self._tx_heights[h] = self._height + 1
                self._notify_available()
                self._fire_notify()
            else:
                # invalid tx: allow future resubmission (reference :259-264)
                self._cache.pop(h, None)
        return res

    def _verify_signed(self, tx: bytes):
        """Envelope signature gate: None when tx may proceed to the app,
        else the rejecting `Result`.  ed25519 lanes ride the batch plane
        (mempool class — preempted by consensus votes); a `DeviceFault`
        that survives the supervised ladder falls back to the scalar
        verifier rather than rejecting a possibly-valid tx."""
        try:
            parsed = parse_signed_tx(tx)
        except ValueError as e:
            return Result(code=ERR_ENCODING,
                          log=f"bad signed-tx envelope: {e}")
        if parsed is None:
            return None
        scheme, pub, sig, payload = parsed
        digest = hashlib.sha256(payload).digest()
        from tendermint_tpu import batchplane
        if scheme == "secp256k1":
            from tendermint_tpu.crypto import secp256k1
            if not secp256k1.AVAILABLE:
                return Result(code=ERR_ENCODING,
                              log="secp256k1 support unavailable")
            ok = bool(batchplane.verify_secp(
                [(pub, digest, sig)], producer="mempool",
                klass=batchplane.CLASS_MEMPOOL)[0])
        else:
            import numpy as np
            try:
                ok = bool(batchplane.verify_batch(
                    np.frombuffer(pub, np.uint8).reshape(1, 32),
                    np.frombuffer(digest, np.uint8).reshape(1, 32),
                    np.frombuffer(sig, np.uint8).reshape(1, 64),
                    producer="mempool",
                    klass=batchplane.CLASS_MEMPOOL)[0])
            except DeviceFault:
                from tendermint_tpu.types.keys import _verify_memo
                ok = _verify_memo(pub, digest, sig)
        if not ok:
            return Result(code=ERR_BAD_SIG,
                          log=f"invalid {scheme} tx signature")
        return None

    def _notify_available(self):
        if (self._txs_available_cb is not None and
                not self._notified_available and self._txs):
            self._notified_available = True
            self._txs_available_cb(self._height + 1)

    def set_txs_available_callback(self, cb):
        """Height-gated fire-once-per-height notification
        (reference `:99-104,277-294`)."""
        self._txs_available_cb = cb

    # -- WAL recovery (SURVEY §5 checkpoint layer 5) ----------------------
    def recover_wal(self, committed=None) -> int:
        """Re-admit journalled txs after a crash (call once at boot, after
        the app handshake restored app state).  Entries are re-run through
        CheckTx; `committed` (tx_bytes -> bool), when given, drops journal
        entries already committed to a block (e.g. via the tx index) so a
        crash between block commit and journal compaction does not re-admit
        them — apps whose CheckTx accepts anything (kvstore) would
        otherwise see at-least-once redelivery.  Without `committed` the
        contract IS at-least-once: the app's CheckTx must reject replays
        of committed txs.  A torn tail is truncated.  Returns the number
        of txs re-admitted."""
        if not self._wal_path:
            return 0
        try:
            with open(self._wal_path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return 0
        txs, off = [], 0
        while off + 4 <= len(data):
            n = int.from_bytes(data[off:off + 4], "big")
            if off + 4 + n > len(data):
                break                      # torn tail from a mid-write crash
            txs.append(data[off + 4:off + 4 + n])
            off += 4 + n
        readmitted = 0
        self._recovering = True
        try:
            for tx in txs:
                if committed is not None and committed(tx):
                    with self._lock:
                        # permanently dedupe, like update(): a peer
                        # gossiping or a client rebroadcasting this tx
                        # after the restart must not re-admit it either
                        self._cache[Tx(tx).hash] = None
                    continue
                res = self.check_tx(tx)
                if res is not None and res.is_ok:
                    readmitted += 1
        finally:
            self._recovering = False
        with self._lock:
            self._rewrite_wal()
        return readmitted

    # -- queries ---------------------------------------------------------
    def size(self) -> int:
        with self._lock:
            return len(self._txs)

    def height(self) -> int:
        """Last committed height this pool was updated to (gossip gate)."""
        return self._height

    def reap(self, max_txs: int) -> list[bytes]:
        """First N txs in order for a proposal (reference `:298-324`)."""
        with self._lock:
            out = []
            for tx in self._txs.values():
                if 0 <= max_txs <= len(out):
                    break
                out.append(tx)
            return out

    def txs_after(self, n: int) -> list[bytes]:
        """Gossip helper: txs from position n onward."""
        with self._lock:
            return list(self._txs.values())[n:]

    def txs_with_heights(self) -> list[tuple[bytes, bytes, int]]:
        """Gossip helper: (hash, tx, admission height) triples in pool
        order — the hash rides along so broadcast sweeps need not
        recompute it per tx per peer."""
        with self._lock:
            return [(h, tx, self._tx_heights.get(h, 0))
                    for h, tx in self._txs.items()]

    # -- post-commit -----------------------------------------------------
    def update(self, height: int, committed_txs: list[bytes]) -> None:
        """Drop committed txs, recheck the rest (reference `:329-391`).
        Caller (apply_block) already holds the lock; _lock is an RLock,
        so taking it again here is free — and keeps the pool consistent
        if update is ever reached without the outer lock()."""
        with self._lock:
            self._height = height
            self._notified_available = False
            for tx in committed_txs:
                h = Tx(tx).hash
                self._txs.pop(h, None)
                self._tx_heights.pop(h, None)
                self._cache[h] = None   # committed: permanently deduped
            if self.recheck_enabled and self._txs:
                survivors = OrderedDict()
                for h, tx in self._txs.items():
                    if self.proxy.check_tx(tx).is_ok:
                        survivors[h] = tx
                    else:
                        self._tx_heights.pop(h, None)
                self._txs = survivors
            # compact the journal to the surviving pool: committed txs
            # must not be re-admitted (re-EXECUTED) by recover_wal
            self._rewrite_wal()
            if self._txs:
                self._notify_available()

    def _rewrite_wal(self) -> None:
        """Atomically rewrite the journal to exactly the current pool
        (temp + rename: a crash mid-rewrite leaves the old journal, whose
        extra entries are merely re-checked, never the empty file a
        truncate-in-place would)."""
        if not self._wal_path:
            return
        if self._wal is not None:
            self._wal.close()
        tmp = self._wal_path + ".tmp"
        with open(tmp, "wb") as f:
            for tx in self._txs.values():
                f.write(len(tx).to_bytes(4, "big") + tx)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._wal_path)
        self._wal = open(self._wal_path, "ab")

    def flush(self) -> None:
        with self._lock:
            self._txs.clear()
            self._tx_heights.clear()
            self._cache.clear()
            self._rewrite_wal()   # journal == pool, or recovery resurrects

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
