"""Ordered transaction pool gated by app CheckTx.

Reference: `mempool/mempool.go` — txs enter via CheckTx on the dedicated
mempool ABCI conn (`:166-205`), LRU dedup cache of 100k (`:51,410-469`),
`Reap` for proposals (`:298-324`), post-commit `Update` + recheck pipeline
(`:329-391`), `TxsAvailable` height-gated notification (`:99-104,277-294`),
and the lock consensus holds across app Commit (`state/execution.go:248`).

The reference's concurrent linked list (tmlibs/clist) becomes an ordered
dict under one re-entrant lock: iteration order == insertion order, O(1)
removal on update, safe concurrent CheckTx from RPC threads.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from tendermint_tpu.types.tx import Tx


class Mempool:
    def __init__(self, proxy_mempool_conn, config=None, wal_path: str = ""):
        self.proxy = proxy_mempool_conn
        cache_size = config.cache_size if config else 100_000
        self.recheck_enabled = config.recheck if config else True
        self._txs: OrderedDict[bytes, bytes] = OrderedDict()  # hash -> tx
        self._cache: OrderedDict[bytes, None] = OrderedDict()
        self._cache_size = cache_size
        self._lock = threading.RLock()
        self._height = 0
        self._notified_available = False
        self._txs_available_cb = None
        self._wal_path = wal_path
        self._wal = open(wal_path, "ab") if wal_path else None

    # -- locking across app Commit (reference state/execution.go:248) ----
    def lock(self):
        self._lock.acquire()

    def unlock(self):
        self._lock.release()

    # -- ingestion -------------------------------------------------------
    def check_tx(self, tx: bytes):
        """Admit via app CheckTx; returns the app Result or None when the
        tx is a cache duplicate (reference `:166-205`).

        The app call happens UNDER the mempool lock: consensus holds this
        lock across app Commit + update (reference proxyMtx semantics), so
        no tx can validate against a half-committed app and then slip into
        the pool after the recheck pass.
        """
        h = Tx(tx).hash
        with self._lock:
            if h in self._cache:
                return None
            self._cache[h] = None
            while len(self._cache) > self._cache_size:
                self._cache.popitem(last=False)
            res = self.proxy.check_tx(tx)
            if res.is_ok:
                if self._wal is not None:
                    self._wal.write(len(tx).to_bytes(4, "big") + tx)
                    self._wal.flush()
                self._txs[h] = tx
                self._notify_available()
            else:
                # invalid tx: allow future resubmission (reference :259-264)
                self._cache.pop(h, None)
        return res

    def _notify_available(self):
        if (self._txs_available_cb is not None and
                not self._notified_available and self._txs):
            self._notified_available = True
            self._txs_available_cb(self._height + 1)

    def set_txs_available_callback(self, cb):
        """Height-gated fire-once-per-height notification
        (reference `:99-104,277-294`)."""
        self._txs_available_cb = cb

    # -- queries ---------------------------------------------------------
    def size(self) -> int:
        with self._lock:
            return len(self._txs)

    def reap(self, max_txs: int) -> list[bytes]:
        """First N txs in order for a proposal (reference `:298-324`)."""
        with self._lock:
            out = []
            for tx in self._txs.values():
                if 0 <= max_txs <= len(out):
                    break
                out.append(tx)
            return out

    def txs_after(self, n: int) -> list[bytes]:
        """Gossip helper: txs from position n onward."""
        with self._lock:
            return list(self._txs.values())[n:]

    # -- post-commit -----------------------------------------------------
    def update(self, height: int, committed_txs: list[bytes]) -> None:
        """Drop committed txs, recheck the rest (reference `:329-391`).
        Caller (apply_block) already holds the lock."""
        self._height = height
        self._notified_available = False
        for tx in committed_txs:
            h = Tx(tx).hash
            self._txs.pop(h, None)
            self._cache[h] = None   # committed: permanently deduped
        if self.recheck_enabled and self._txs:
            survivors = OrderedDict()
            for h, tx in self._txs.items():
                if self.proxy.check_tx(tx).is_ok:
                    survivors[h] = tx
            self._txs = survivors
        if self._txs:
            self._notify_available()

    def flush(self) -> None:
        with self._lock:
            self._txs.clear()
            self._cache.clear()

    def close(self) -> None:
        if self._wal is not None:
            self._wal.close()
