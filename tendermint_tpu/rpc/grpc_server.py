"""gRPC broadcast API — the reference's second RPC surface.

Reference: `rpc/grpc/api.go:14-32` + `rpc/grpc/client_server.go`: a tiny
gRPC service (`Ping`, `BroadcastTx`) next to the JSON-RPC server, served
when `rpc.grpc_laddr` is configured.  Real gRPC (HTTP/2) transport via
grpcio generic handlers; message bodies use the framework's fixed-layout
binary codec (`types.codec`) rather than generated protobuf stubs — the
same in-repo codec every other wire surface uses, so there is no
generated-code bulk to vendor.

Wire formats:
  Ping:        request b"" -> response b""
  BroadcastTx: request  lp_bytes(tx)
               response u32(check_code) lp(check_data) lp(check_log)
                        u32(deliver_code) lp(deliver_data) lp(deliver_log)
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

from tendermint_tpu.types.codec import Reader, lp_bytes, u32
from tendermint_tpu.utils.log import get_logger

log = get_logger("grpc")

SERVICE = "tendermint_tpu.BroadcastAPI"


def _ident(b: bytes) -> bytes:
    return b


def _encode_result(d: dict) -> bytes:
    check = d.get("check_tx") or {}
    deliver = d.get("deliver_tx") or {}

    def enc(r: dict) -> bytes:
        return (u32(int(r.get("code", 0))) +
                lp_bytes(bytes.fromhex(r.get("data", "") or "")) +
                lp_bytes((r.get("log", "") or "").encode()))

    return enc(check) + enc(deliver)


def decode_result(b: bytes) -> dict:
    r = Reader(b)

    def dec() -> dict:
        return {"code": r.u32(), "data": r.lp_bytes().hex(),
                "log": r.lp_bytes().decode()}

    check = dec()
    deliver = dec()
    r.expect_done()
    return {"check_tx": check, "deliver_tx": deliver}


class GRPCServer:
    """Serves Ping/BroadcastTx over gRPC next to the JSON-RPC server."""

    def __init__(self, routes, laddr: str):
        import grpc
        self._routes = routes
        addr = laddr.replace("tcp://", "")
        self._server = grpc.server(ThreadPoolExecutor(4))

        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                name = handler_call_details.method
                if name == f"/{SERVICE}/Ping":
                    return grpc.unary_unary_rpc_method_handler(
                        lambda req, ctx: b"",
                        request_deserializer=_ident,
                        response_serializer=_ident)
                if name == f"/{SERVICE}/BroadcastTx":
                    return grpc.unary_unary_rpc_method_handler(
                        outer._broadcast_tx,
                        request_deserializer=_ident,
                        response_serializer=_ident)
                return None

        self._server.add_generic_rpc_handlers((Handler(),))
        self._port = self._server.add_insecure_port(addr)
        self.laddr = addr.rsplit(":", 1)[0] + f":{self._port}"

    def _broadcast_tx(self, req: bytes, ctx) -> bytes:
        tx = Reader(req).lp_bytes()
        res = self._routes.broadcast_tx_commit({"tx": "0x" + tx.hex()})
        return _encode_result(res)

    def start(self) -> None:
        self._server.start()
        log.info("grpc broadcast api serving", laddr=self.laddr)

    def stop(self) -> None:
        self._server.stop(grace=0.5)


class GRPCClient:
    """Minimal client for the broadcast API (reference
    `rpc/grpc/client_server.go` StartGRPCClient)."""

    def __init__(self, addr: str):
        import grpc
        addr = addr.replace("tcp://", "")
        self._chan = grpc.insecure_channel(addr)
        self._ping = self._chan.unary_unary(
            f"/{SERVICE}/Ping", request_serializer=_ident,
            response_deserializer=_ident)
        self._btx = self._chan.unary_unary(
            f"/{SERVICE}/BroadcastTx", request_serializer=_ident,
            response_deserializer=_ident)

    def ping(self) -> bool:
        return self._ping(b"") == b""

    def broadcast_tx(self, tx: bytes) -> dict:
        return decode_result(self._btx(lp_bytes(tx)))

    def close(self) -> None:
        self._chan.close()
