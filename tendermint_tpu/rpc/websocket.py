"""Minimal RFC 6455 WebSocket server glue for event subscriptions.

Reference: the rpc lib's WebSocketManager bridging the event switch to
subscribers (`rpc/lib/server/handlers.go`, `node/node.go:338-341`).
Implemented directly over the HTTP handler's socket: handshake, text and
close/ping frames — enough for subscribe/unsubscribe streams.
"""

from __future__ import annotations

import base64
import hashlib
import json
import struct
import threading

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


def accept_key(client_key: str) -> str:
    digest = hashlib.sha1((client_key + _WS_GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def send_text(sock, payload: str) -> None:
    data = payload.encode()
    header = bytes([0x81])  # FIN + text
    n = len(data)
    if n < 126:
        header += bytes([n])
    elif n < 1 << 16:
        header += bytes([126]) + struct.pack(">H", n)
    else:
        header += bytes([127]) + struct.pack(">Q", n)
    sock.sendall(header + data)


def send_close(sock) -> None:
    try:
        sock.sendall(bytes([0x88, 0x00]))
    except OSError:
        pass


def _read_exact(rfile, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = rfile.read(n - len(buf))
        if not chunk:
            raise ConnectionError("ws closed")
        buf += chunk
    return buf


def read_frame(rfile) -> tuple[int, bytes]:
    """Returns (opcode, payload); raises ConnectionError on EOF."""
    b1, b2 = _read_exact(rfile, 2)
    opcode = b1 & 0x0F
    masked = b2 & 0x80
    n = b2 & 0x7F
    if n == 126:
        n = struct.unpack(">H", _read_exact(rfile, 2))[0]
    elif n == 127:
        n = struct.unpack(">Q", _read_exact(rfile, 8))[0]
    mask = _read_exact(rfile, 4) if masked else b"\x00" * 4
    payload = bytearray(_read_exact(rfile, n))
    if masked:
        for i in range(n):
            payload[i] ^= mask[i % 4]
    return opcode, bytes(payload)


class WSSession:
    """One websocket connection: JSON-RPC subscribe/unsubscribe requests
    in, event notifications out."""

    def __init__(self, handler, node, routes):
        self.handler = handler
        self.sock = handler.connection
        self.node = node
        self.routes = routes
        self.sub_id = f"ws-{id(self)}"
        self._send_lock = threading.Lock()
        self._subs: set[str] = set()

    def _notify(self, event: str):
        def cb(data):
            try:
                with self._send_lock:
                    send_text(self.sock, json.dumps({
                        "jsonrpc": "2.0", "method": "event",
                        "params": {"event": event,
                                   "data": _event_data_json(data)}}))
            except OSError:
                pass
        return cb

    def run(self) -> None:
        try:
            while True:
                opcode, payload = read_frame(self.handler.rfile)
                if opcode == 0x8:      # close
                    break
                if opcode == 0x9:      # ping -> pong
                    with self._send_lock:
                        self.sock.sendall(bytes([0x8A, 0x00]))
                    continue
                if opcode not in (0x1, 0x2):
                    continue
                self._handle(payload)
        except (ConnectionError, OSError):
            pass
        finally:
            for event in self._subs:
                self.node.evsw.unsubscribe(self.sub_id, event)
            send_close(self.sock)

    def _handle(self, payload: bytes) -> None:
        req = None
        try:
            req = json.loads(payload)
            method = req.get("method")
            params = req.get("params") or {}
            rid = req.get("id")
            if method == "subscribe":
                event = params["event"]
                self._subs.add(event)
                self.node.evsw.subscribe(self.sub_id, event,
                                         self._notify(event))
                result = {"subscribed": event}
            elif method == "unsubscribe":
                event = params["event"]
                self._subs.discard(event)
                self.node.evsw.unsubscribe(self.sub_id, event)
                result = {"unsubscribed": event}
            elif method in self.routes.table:
                result = self.routes.table[method](params)
            else:
                raise ValueError(f"unknown method {method!r}")
            out = {"jsonrpc": "2.0", "id": rid, "result": result}
        except Exception as e:
            out = {"jsonrpc": "2.0", "id": req.get("id") if
                   isinstance(req, dict) else None,
                   "error": {"code": -32603, "message": str(e)}}
        with self._send_lock:
            send_text(self.sock, json.dumps(out))


def _event_data_json(data):
    """Best-effort JSON projection of event payloads."""
    from tendermint_tpu.types.block import Block, Header
    if isinstance(data, Block):
        return {"height": data.height, "hash": data.hash().hex(),
                "num_txs": len(data.txs)}
    if isinstance(data, Header):
        return {"height": data.height, "chain_id": data.chain_id}
    if hasattr(data, "__dict__"):
        return {k: (v.hex() if isinstance(v, bytes) else v)
                for k, v in vars(data).items()
                if isinstance(v, (int, float, str, bytes, bool))}
    return str(data)
