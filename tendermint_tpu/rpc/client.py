"""RPC clients: HTTP, in-process Local, and a minimal WebSocket client.

Reference: `rpc/client/` — `Client` interface with HTTP and Local
implementations (`interface.go`, `httpclient.go`, `localclient.go`).
"""

from __future__ import annotations

import base64
import json
import os
import socket
import urllib.request

from tendermint_tpu.rpc import websocket as ws


class RPCError(Exception):
    pass


class HTTPClient:
    """JSON-RPC over HTTP POST (reference httpclient.go)."""

    def __init__(self, addr: str, timeout: float = 65.0):
        self.addr = addr.rstrip("/")
        self.timeout = timeout
        self._id = 0

    def call(self, method: str, **params):
        self._id += 1
        body = json.dumps({"jsonrpc": "2.0", "id": self._id,
                           "method": method, "params": params}).encode()
        req = urllib.request.Request(
            self.addr, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                out = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            out = json.loads(e.read())
        if "error" in out and out["error"]:
            raise RPCError(out["error"].get("message", str(out["error"])))
        return out["result"]

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda **params: self.call(name, **params)


class LocalClient:
    """Direct in-process dispatch (reference localclient.go)."""

    def __init__(self, node):
        from tendermint_tpu.rpc.routes import Routes
        self._routes = Routes(node)

    def call(self, method: str, **params):
        fn = self._routes.table.get(method)
        if fn is None:
            raise RPCError(f"unknown method {method!r}")
        return fn(params)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return lambda **params: self.call(name, **params)


class WSClient:
    """Minimal client for /websocket subscriptions (tests, tooling)."""

    def __init__(self, addr: str, timeout: float = 30.0):
        # addr is the http addr; connect raw TCP and upgrade
        assert addr.startswith("http://")
        host, port = addr[7:].rstrip("/").rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        req = (f"GET /websocket HTTP/1.1\r\nHost: {host}\r\n"
               f"Upgrade: websocket\r\nConnection: Upgrade\r\n"
               f"Sec-WebSocket-Key: {key}\r\n"
               f"Sec-WebSocket-Version: 13\r\n\r\n")
        self._sock.sendall(req.encode())
        # read the 101 response headers
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = self._sock.recv(4096)
            if not chunk:
                raise ConnectionError("ws handshake failed")
            buf += chunk
        if b"101" not in buf.split(b"\r\n", 1)[0]:
            raise ConnectionError(f"ws handshake rejected: {buf[:200]!r}")
        self._rfile = self._sock.makefile("rb")
        self._id = 0

    def _send(self, obj: dict) -> None:
        # client frames must be masked
        data = json.dumps(obj).encode()
        mask = os.urandom(4)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(data))
        n = len(data)
        if n < 126:
            header = bytes([0x81, 0x80 | n])
        else:
            import struct
            header = bytes([0x81, 0x80 | 126]) + struct.pack(">H", n)
        self._sock.sendall(header + mask + masked)

    def subscribe(self, event: str) -> None:
        self._id += 1
        self._send({"jsonrpc": "2.0", "id": self._id, "method": "subscribe",
                    "params": {"event": event}})
        self.recv()   # ack

    def recv(self) -> dict:
        while True:
            opcode, payload = ws.read_frame(self._rfile)
            if opcode == 0x8:
                raise ConnectionError("ws closed")
            if opcode in (0x1, 0x2):
                return json.loads(payload)

    def close(self) -> None:
        try:
            ws.send_close(self._sock)
            self._sock.close()
        except OSError:
            pass
