"""JSON-RPC server: HTTP POST, GET URI endpoints, and WebSocket events.

Reference: `rpc/lib/server/handlers.go` — every route is exposed both as
a JSON-RPC method on POST / and as a GET URI endpoint (`:26-70`), plus a
`/websocket` upgrade for subscriptions.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

from tendermint_tpu.rpc.routes import Routes
from tendermint_tpu.rpc import websocket as ws
from tendermint_tpu.utils import metrics


class RPCServer:
    def __init__(self, node, rpc_config):
        self.node = node
        self.routes = Routes(node)
        laddr = rpc_config.laddr
        assert laddr.startswith("tcp://")
        host, port = laddr[6:].rsplit(":", 1)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _respond(self, code: int, body: dict):
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                parsed = urlparse(self.path)
                method = parsed.path.strip("/")
                if method == "websocket":
                    self._upgrade_websocket()
                    return
                if method == "metrics":
                    # Prometheus text exposition — plain text, not
                    # JSON-RPC, so it bypasses the method table
                    data = metrics.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4; "
                                     "charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if method == "":
                    self._respond(200, {
                        "routes": sorted(outer.routes.table) +
                        ["websocket (ws upgrade)"]})
                    return
                params = dict(parse_qsl(parsed.query))
                self._call(method, params, rid=-1)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    self._respond(400, {"error": {"code": -32700,
                                                  "message": "parse error"}})
                    return
                self._call(req.get("method", ""), req.get("params") or {},
                           rid=req.get("id"))

            def _call(self, method, params, rid):
                fn = outer.routes.table.get(method)
                if fn is None:
                    self._respond(404, {
                        "jsonrpc": "2.0", "id": rid,
                        "error": {"code": -32601,
                                  "message": f"unknown method {method!r}"}})
                    return
                try:
                    result = fn(params)
                    self._respond(200, {"jsonrpc": "2.0", "id": rid,
                                        "result": result})
                except Exception as e:
                    self._respond(500, {"jsonrpc": "2.0", "id": rid,
                                        "error": {"code": -32603,
                                                  "message": str(e)}})

            def _upgrade_websocket(self):
                key = self.headers.get("Sec-WebSocket-Key")
                if not key:
                    self._respond(400, {"error": {
                        "code": -32600, "message": "not a ws handshake"}})
                    return
                self.send_response(101, "Switching Protocols")
                self.send_header("Upgrade", "websocket")
                self.send_header("Connection", "Upgrade")
                self.send_header("Sec-WebSocket-Accept", ws.accept_key(key))
                self.end_headers()
                ws.WSSession(self, outer.node, outer.routes).run()
                self.close_connection = True

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    @property
    def addr(self) -> str:
        return f"http://{self._httpd.server_address[0]}:{self.port}"

    def start(self) -> None:
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="rpc-http")
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
