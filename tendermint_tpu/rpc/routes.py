"""RPC route handlers: node introspection and the tx write path.

Reference: `rpc/core/routes.go:8-46` (route table), `rpc/core/mempool.go`
(broadcast_tx_*; `BroadcastTxCommit` = CheckTx + subscribe to the per-tx
DeliverTx event with a timeout, `:48-104`), `rpc/core/pipe.go` (node
wiring).  Handlers return JSON-serializable dicts.
"""

from __future__ import annotations

import threading

from tendermint_tpu.types import merkle
from tendermint_tpu.types.events import event_tx
from tendermint_tpu.types.tx import Tx

BROADCAST_TX_COMMIT_TIMEOUT = 60.0   # reference: 60s-120s


def _hexb(b: bytes) -> str:
    return b.hex()


def _parse_tx(params: dict) -> bytes:
    tx = params.get("tx")
    if tx is None:
        raise ValueError("missing param: tx")
    if isinstance(tx, str):
        if tx.startswith("0x"):
            tx = tx[2:]
        return bytes.fromhex(tx)
    raise ValueError("tx must be a hex string")


def _result_dict(res) -> dict:
    return {"code": res.code, "data": _hexb(res.data), "log": res.log}


def _block_dict(block) -> dict:
    h = block.header
    return {
        "header": {
            "chain_id": h.chain_id, "height": h.height,
            "time_ns": h.time_ns, "num_txs": h.num_txs,
            "last_block_id": {"hash": _hexb(h.last_block_id.hash)},
            "last_commit_hash": _hexb(h.last_commit_hash),
            "data_hash": _hexb(h.data_hash),
            "validators_hash": _hexb(h.validators_hash),
            "app_hash": _hexb(h.app_hash),
        },
        "block_hash": _hexb(block.hash()),
        "txs": [_hexb(tx) for tx in block.txs],
        "last_commit": {
            "block_id": {"hash": _hexb(block.last_commit.block_id.hash)},
            "precommits": sum(v is not None
                              for v in block.last_commit.precommits),
        },
    }


class Routes:
    """One instance per node; `table` maps method name -> handler."""

    def __init__(self, node):
        self.node = node
        self.table = {
            "status": self.status,
            "abci_info": self.abci_info,
            "abci_query": self.abci_query,
            "block": self.block,
            "blockchain": self.blockchain,
            "commit": self.commit,
            "validators": self.validators,
            "genesis": self.genesis,
            "dump_consensus_state": self.dump_consensus_state,
            "broadcast_tx_async": self.broadcast_tx_async,
            "broadcast_tx_sync": self.broadcast_tx_sync,
            "broadcast_tx_commit": self.broadcast_tx_commit,
            "unconfirmed_txs": self.unconfirmed_txs,
            "num_unconfirmed_txs": self.num_unconfirmed_txs,
            "tx": self.tx,
            "net_info": self.net_info,
            "evidence": self.evidence,
        }
        if getattr(node.config.rpc, "unsafe", False):
            # operator-only routes, served only with rpc.unsafe = true
            # (reference rpc/core/routes.go:30-46 AddUnsafeRoutes — the
            # profiler/debug API is unsafe-gated there too)
            self.table.update({
                "unsafe_flush_mempool": self.unsafe_flush_mempool,
                "unsafe_dial_seeds": self.unsafe_dial_seeds,
                "debug_stacks": self.debug_stacks,
                "debug_trace_start": self.debug_trace_start,
                "debug_trace_stop": self.debug_trace_stop,
                "debug_flight_recorder": self.debug_flight_recorder,
                "debug_doctor": self.debug_doctor,
                "debug_timeline": self.debug_timeline,
                "debug_bench_history": self.debug_bench_history,
            })

    # -- info routes ----------------------------------------------------
    def status(self, params: dict) -> dict:
        return self.node.status()

    def abci_info(self, params: dict) -> dict:
        info = self.node.proxy_app.query.info()
        return {"data": info.data, "version": info.version,
                "last_block_height": info.last_block_height,
                "last_block_app_hash": _hexb(info.last_block_app_hash)}

    def abci_query(self, params: dict) -> dict:
        data = params.get("data", "")
        if data.startswith("0x"):       # same prefix tolerance as the tx
            data = data[2:]             # routes (reference accepts both)
        data = bytes.fromhex(data)
        path = params.get("path", "/")
        height = int(params.get("height", 0))
        prove = bool(params.get("prove", False))
        r = self.node.proxy_app.query.query(data, path, height, prove)
        return {"code": r.code, "key": _hexb(r.key), "value": _hexb(r.value),
                "height": r.height, "log": r.log}

    def block(self, params: dict) -> dict:
        height = int(params["height"])
        block = self.node.block_store.load_block(height)
        if block is None:
            raise ValueError(f"no block at height {height}")
        return {"block": _block_dict(block)}

    def blockchain(self, params: dict) -> dict:
        """Reference rpc/core/blocks.go BlockchainInfo: metas for a range."""
        store = self.node.block_store
        max_h = int(params.get("maxHeight", store.height) or store.height)
        max_h = min(max_h, store.height)
        min_h = int(params.get("minHeight", max(1, max_h - 19)))
        metas = []
        for h in range(max_h, min_h - 1, -1):
            m = store.load_block_meta(h)
            if m is None:
                break
            metas.append({"height": m.height, "num_txs": m.num_txs,
                          "block_hash": _hexb(m.block_id.hash)})
        return {"last_height": store.height, "block_metas": metas}

    def commit(self, params: dict) -> dict:
        height = int(params["height"])
        store = self.node.block_store
        commit = (store.load_seen_commit(height)
                  if height == store.height
                  else store.load_block_commit(height))
        if commit is None:
            raise ValueError(f"no commit for height {height}")
        return {
            "canonical": height != store.height,
            "block_id": {"hash": _hexb(commit.block_id.hash)},
            "precommits": sum(v is not None for v in commit.precommits),
            "height": height,
        }

    def validators(self, params: dict) -> dict:
        vs = self.node.state.validators
        # snapshot the accum vector under the consensus lock: the commit
        # path rotates _accums in place, and an unlocked element-by-element
        # read can interleave with a rotation and report a mix of pre- and
        # post-increment priorities
        mtx = getattr(getattr(self.node, "consensus", None), "_mtx", None)
        if mtx is not None:
            with mtx:
                accums = vs._accums.copy()
        else:
            accums = vs._accums.copy()
        return {
            "block_height": self.node.state.last_block_height,
            "validators": [
                {"address": _hexb(v.address),
                 "pub_key": _hexb(v.pub_key.bytes_),
                 "voting_power": v.voting_power,
                 "accum": int(accums[i])}
                for i, v in enumerate(vs.validators)
            ],
        }

    def genesis(self, params: dict) -> dict:
        import json
        return {"genesis": json.loads(self.node.genesis_doc.to_json())}

    def dump_consensus_state(self, params: dict) -> dict:
        """Full RoundState + per-peer round states (reference
        `rpc/core/routes.go:21`, `rpc/core/consensus.go`)."""
        peer_states = {}
        sw = self.node.switch
        if sw is not None:
            for p in sw.peers():
                ps = p.get("consensus")
                if ps is not None:
                    peer_states[p.id] = ps.summary()
        return {"round_state": self.node.consensus.get_round_state_dump(),
                "peer_round_states": peer_states}

    def evidence(self, params: dict) -> dict:
        """Pending equivocation proofs from the evidence pool."""
        def vote_d(v):
            return {"validator": _hexb(v.validator_address),
                    "height": v.height, "round": v.round, "type": v.type,
                    "block_hash": _hexb(v.block_id.hash)}
        pool = getattr(self.node, "evidence_pool", None)
        if pool is None:
            return {"evidence": [], "count": 0}
        evs = pool.pending()
        return {"count": len(evs),
                "evidence": [{"vote_a": vote_d(e.vote_a),
                              "vote_b": vote_d(e.vote_b)} for e in evs]}

    # -- unsafe operator routes (reference rpc/core/routes.go:30-36) ------
    def unsafe_flush_mempool(self, params: dict) -> dict:
        self.node.mempool.flush()
        return {"flushed": True}

    def unsafe_dial_seeds(self, params: dict) -> dict:
        from tendermint_tpu.p2p.types import NetAddress
        seeds = params.get("seeds") or []
        if isinstance(seeds, str):
            seeds = [s for s in seeds.split(",") if s]
        sw = self.node.switch
        if sw is None:
            raise ValueError("node has no p2p switch")
        for s in seeds:
            sw.dial_peer_async(NetAddress.parse(str(s)))
        return {"dialing": list(map(str, seeds))}

    # -- debug/profiling routes (reference pprof endpoints analog) --------
    def debug_stacks(self, params: dict) -> dict:
        from tendermint_tpu.utils import trace
        return {"threads": trace.thread_stacks()}

    def debug_trace_start(self, params: dict) -> dict:
        import os
        import re
        from tendermint_tpu.utils import trace
        # the name is an RPC param: allow only a flat subdirectory under
        # the fixed trace base (no path escape / arbitrary-dir writes)
        name = str(params.get("name") or "trace")
        if (not re.fullmatch(r"[A-Za-z0-9._-]{1,64}", name)
                or set(name) == {"."}):
            raise ValueError("trace name must match [A-Za-z0-9._-]{1,64}")
        base = os.path.realpath("/tmp/tendermint_tpu_trace")
        d = os.path.realpath(os.path.join(base, name))
        if os.path.dirname(d) != base:
            raise ValueError("trace name escapes the trace directory")
        return {"started": trace.start_device_trace(d), "dir": d}

    def debug_trace_stop(self, params: dict) -> dict:
        from tendermint_tpu.utils import trace
        return {"dir": trace.stop_device_trace()}

    def debug_flight_recorder(self, params: dict) -> dict:
        """Dump the in-process flight recorder.  format="chrome" returns
        the Chrome trace-event JSON (load in Perfetto / chrome://tracing);
        the default "spans" form is the raw oldest-first span list.
        name=SUBSTR keeps only matching spans, last=N the N most recent
        (filters apply server-side so a 16k-span ring doesn't cross the
        wire to answer a question about its tail).  clear=true empties
        the ring after the dump."""
        from tendermint_tpu.utils import tracing
        rec = tracing.RECORDER
        fmt = str(params.get("format", "spans"))
        name = str(params.get("name", "") or "")
        last = int(params.get("last", 0) or 0)

        def _filter(evs, ts_key="ts"):
            if name:
                evs = [e for e in evs if name in e.get("name", "")]
            if last > 0:
                evs = sorted(evs, key=lambda e: e.get(ts_key, 0))[-last:]
            return evs

        if fmt == "chrome":
            trace = rec.to_chrome_trace()
            if name or last:
                meta = [e for e in trace["traceEvents"]
                        if e.get("ph") == "M"]
                spans = [e for e in trace["traceEvents"]
                         if e.get("ph") != "M"]
                trace["traceEvents"] = _filter(spans) + meta
            out = {"trace": trace}
        elif fmt == "spans":
            out = {"spans": _filter(rec.snapshot())}
        else:
            raise ValueError("format must be 'spans' or 'chrome'")
        out.update({"total": rec.total, "dropped": rec.dropped,
                    "capacity": rec.capacity})
        if str(params.get("clear", "")).lower() in ("1", "true", "yes"):
            rec.clear()
        return out

    def debug_timeline(self, params: dict) -> dict:
        """This node's height-lifecycle dump for the mesh collector
        (telemetry/collector.merge_dumps): the canonical per-height
        records from the consensus core's ring, a wall-clock sample for
        cross-node skew normalization, and the local stage histogram.
        last=N keeps the N most recent heights."""
        import time as _time
        from tendermint_tpu.utils.metrics import REGISTRY
        cs = self.node.consensus
        records = list(getattr(cs, "lifecycle", ()))
        last = int(params.get("last", 0) or 0)
        if last > 0:
            records = records[-last:]
        return {"node": cs.node_id or self.node.config.base.moniker,
                "wall_now": _time.time(),
                "records": records,
                "stage_seconds": REGISTRY.consensus_stage_seconds.snapshot()}

    def debug_doctor(self, params: dict) -> dict:
        """Pipeline attribution over the live flight recorder: per-window
        wall-clock partition (compile / transfer / device / scalar /
        idle) and the largest thief of the throughput target."""
        from tendermint_tpu.utils import attribution, tracing
        return {"report": attribution.doctor_report(
            tracing.RECORDER.snapshot())}

    def debug_bench_history(self, params: dict) -> dict:
        """Bench regression ledger entries with deltas vs best prior
        run.  The ledger path is an RPC param: restricted to a flat
        filename in the node's working directory (same containment rule
        as debug_trace_start — no path escape)."""
        import os
        import re
        from tendermint_tpu.utils import ledger
        name = str(params.get("ledger") or ledger.DEFAULT_PATH)
        if (not re.fullmatch(r"[A-Za-z0-9._-]{1,64}", name)
                or set(name) == {"."}):
            raise ValueError("ledger must match [A-Za-z0-9._-]{1,64}")
        base = os.path.realpath(os.getcwd())
        path = os.path.realpath(os.path.join(base, name))
        if os.path.dirname(path) != base:
            raise ValueError("ledger path escapes the working directory")
        entries = ledger.load(path)
        deltas = None
        if entries:
            deltas = ledger.compute_deltas(
                entries[:-1], entries[-1].get("configs") or {})
        return {"entries": entries, "count": len(entries),
                "latest_deltas": deltas}

    def net_info(self, params: dict) -> dict:
        sw = self.node.switch
        if sw is None:
            return {"listening": False, "peers": []}
        return sw.net_info()

    # -- mempool routes (reference rpc/core/mempool.go) ------------------
    def broadcast_tx_async(self, params: dict) -> dict:
        tx = _parse_tx(params)
        tx_hash = Tx(tx).hash
        threading.Thread(target=self.node.mempool.check_tx,
                         args=(tx, tx_hash), daemon=True).start()
        return {"hash": _hexb(tx_hash)}

    def broadcast_tx_sync(self, params: dict) -> dict:
        tx = _parse_tx(params)
        # hash once, share with admission: the response needs it either
        # way, and at flood rates the second sha256 (and even the Tx
        # wrapper allocation) is real budget
        tx_hash = merkle.leaf_hash(tx)
        res = self.node.mempool.check_tx(tx, tx_hash=tx_hash)
        if res is None:
            raise ValueError("tx already in cache")
        return {"code": res.code, "data": res.data.hex(),
                "log": res.log, "hash": tx_hash.hex()}

    def broadcast_tx_commit(self, params: dict) -> dict:
        """CheckTx then wait for the DeliverTx event
        (reference rpc/core/mempool.go:48-104)."""
        tx = _parse_tx(params)
        tx_hash = Tx(tx).hash
        done = threading.Event()
        result: dict = {}

        def on_deliver(tx_event):
            result["deliver"] = tx_event
            done.set()

        key = event_tx(tx_hash)
        sub_id = f"btc-{tx_hash.hex()[:16]}"
        self.node.evsw.subscribe(sub_id, key, on_deliver)
        try:
            check = self.node.mempool.check_tx(tx, tx_hash=tx_hash)
            if check is None:
                raise ValueError("tx already in cache")
            if not check.is_ok:
                return {"check_tx": _result_dict(check),
                        "hash": _hexb(tx_hash), "height": 0}
            if not done.wait(BROADCAST_TX_COMMIT_TIMEOUT):
                raise TimeoutError("timed out waiting for tx commit")
            ev = result["deliver"]
            return {"check_tx": _result_dict(check),
                    "deliver_tx": _result_dict(ev.result),
                    "hash": _hexb(tx_hash), "height": ev.height}
        finally:
            self.node.evsw.unsubscribe(sub_id, key)

    def unconfirmed_txs(self, params: dict) -> dict:
        txs = self.node.mempool.reap(-1)
        return {"n_txs": len(txs), "txs": [_hexb(t) for t in txs]}

    def num_unconfirmed_txs(self, params: dict) -> dict:
        return {"n_txs": self.node.mempool.size(),
                "total_bytes": self.node.mempool.size_bytes()}

    def tx(self, params: dict) -> dict:
        """Tx lookup by hash (kv indexer required)."""
        h = params.get("hash", "")
        if h.startswith("0x"):
            h = h[2:]
        tr = self.node.tx_indexer.get(bytes.fromhex(h))
        if tr is None:
            raise ValueError(f"tx {h} not found")
        return {"height": tr.height, "index": tr.index,
                "tx": _hexb(tr.tx), "tx_result": _result_dict(tr.result)}
