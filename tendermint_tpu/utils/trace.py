"""Tracing/profiling hooks: thread-stack dumps + JAX device traces.

The reference exposes pprof-style debug endpoints (`rpc/core` net/http
pprof wiring); the analogs here are:

  * `thread_stacks()` — every live thread's Python stack (the goroutine
    dump analog; invaluable for gossip/consensus deadlock triage),
  * `start_device_trace` / `stop_device_trace` — the JAX profiler
    (XPlane traces viewable in TensorBoard/Perfetto), capturing device
    kernel timelines for the verify/merkle hot plane.

Both are served by the `debug_*` RPC routes (`rpc/routes.py`).
"""

from __future__ import annotations

import sys
import threading
import traceback

from tendermint_tpu.utils.log import get_logger

log = get_logger("trace")

_trace_lock = threading.Lock()
_trace_dir: str | None = None


def thread_stacks() -> dict[str, list[str]]:
    """Name -> formatted stack for every live Python thread."""
    frames = sys._current_frames()
    out = {}
    for t in threading.enumerate():
        f = frames.get(t.ident)
        name = f"{t.name}{'(daemon)' if t.daemon else ''}"
        out[name] = traceback.format_stack(f) if f is not None else []
    return out


def start_device_trace(trace_dir: str) -> bool:
    """Begin a JAX profiler capture; False if one is already running."""
    global _trace_dir
    with _trace_lock:
        if _trace_dir is not None:
            return False
        import jax
        jax.profiler.start_trace(trace_dir)
        _trace_dir = trace_dir
        log.info("device trace started", dir=trace_dir)
        return True


def stop_device_trace() -> str | None:
    """Stop the capture; returns the trace dir (None if none running)."""
    global _trace_dir
    with _trace_lock:
        if _trace_dir is None:
            return None
        import jax
        jax.profiler.stop_trace()
        d, _trace_dir = _trace_dir, None
        log.info("device trace stopped", dir=d)
        return d
