"""Flight recorder: a thread-safe ring buffer of timed spans.

The XPlane capture (`utils/trace.py start_device_trace`) answers "what
did the DEVICE do" at kernel granularity, but only while an operator has
a capture running.  The flight recorder is the complement: an
always-on, bounded record of what the HOST planes did — consensus step
transitions, device batch dispatch/collect, WAL writes, fast-sync pool
events, bench fixture/replay phases — cheap enough to leave recording
in production (one lock + one list store per span) and dumpable after
the fact, like an aircraft FDR.

Spans are written with the context manager::

    with span("verify.dispatch", height=h, lanes=n):
        ...

or, for point events with no duration, ``instant("pool.evict", ...)``.

The buffer is a fixed-capacity ring (TM_FLIGHT_RECORDER_CAP, default
16384 spans): old spans are overwritten, never reallocated, so the
recorder's footprint is constant no matter how long the node runs.
`to_chrome_trace()` renders the Chrome trace-event JSON format that
Perfetto / chrome://tracing / TensorBoard all load, so a flight-recorder
dump and an XPlane capture can be eyeballed side by side.

Served by the `debug_flight_recorder` RPC route (`rpc/routes.py`) and
the `trace` CLI subcommand; the bench harness dumps one per run.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager

# epoch anchor for perf_counter timestamps: spans carry wall-clock start
# times (so traces from different processes line up) but durations from
# the monotonic clock (so an NTP step mid-span cannot go negative)
_EPOCH_T0 = time.time() - time.perf_counter()

PH_SPAN = "X"        # Chrome "complete" event (ts + dur)
PH_INSTANT = "i"     # Chrome "instant" event


def perf_to_epoch(p: float) -> float:
    """Map a time.perf_counter() reading onto the recorder's wall-clock
    axis — for callers recording a span from timestamps they already
    took (e.g. reactor window accounting) instead of via span()."""
    return _EPOCH_T0 + p

# -- span categories ---------------------------------------------------------
# Every span carries a category so the attribution profiler
# (utils/attribution.py) can partition a replay window's wall clock into
# compile / transfer / device-busy / scalar / idle without knowing every
# span name.  Call sites may pass cat= explicitly; otherwise the name
# prefix decides (longest prefix wins).
CAT_PREP = "prep"          # host-side window assembly (hashing, lanes)
CAT_DISPATCH = "dispatch"  # host-side device enqueue (async upload+queue)
CAT_DEVICE = "device"      # wait-for-device-result / sync device calls
CAT_APPLY = "apply"        # host-side ABCI/store application
CAT_COMPILE = "compile"    # XLA compile / first-call executables
CAT_TRANSFER = "transfer"  # host<->device copies
CAT_SCALAR = "scalar"      # scalar/python fallback crypto
# Timeline-plane categories (telemetry/): consensus height-lifecycle
# stages and mesh-collector work.  These never appear in PARTITION so
# they cannot pollute the replay attribution; they exist so lifecycle
# spans are categorized (tmlint span-category) and filterable in traces.
CAT_CONSENSUS = "consensus"  # height lifecycle stages (propose..commit)
CAT_TELEMETRY = "telemetry"  # mesh collector / timeline merge work
# Deliberately-uncategorized: host bookkeeping spans (WAL writes,
# supervised-ladder wrappers whose inner spans carry the categories).
# Passing cat=CAT_NONE skips prefix inference AND keeps the span out of
# the attribution partition — unlike cat=None, which means "infer".
CAT_NONE = ""

_CAT_BY_PREFIX = (
    ("xla.", CAT_COMPILE),
    ("transfer.", CAT_TRANSFER),
    ("scalar.", CAT_SCALAR),
    ("verify.dispatch", CAT_DISPATCH),
    ("verify.collect", CAT_DEVICE),
    ("fastsync.verify", CAT_DEVICE),
    ("bench.verify", CAT_DEVICE),
    ("verify.batch", CAT_DEVICE),
    ("verify.grouped", CAT_DEVICE),
    ("sign.batch", CAT_DEVICE),
    ("bench.prep", CAT_PREP),
    ("bench.dispatch", CAT_DISPATCH),
    ("bench.apply", CAT_APPLY),
    ("fastsync.prepare", CAT_PREP),
    ("fastsync.lookahead", CAT_PREP),
    ("fastsync.apply", CAT_APPLY),
    # timeline plane: lifecycle stages + collector.  consensus spans that
    # ARE device/apply work (vote_microbatch, apply) pass cat= explicitly
    # at the call site, which always wins over this prefix.
    ("consensus.", CAT_CONSENSUS),
    ("telemetry.", CAT_TELEMETRY),
)


def now_epoch() -> float:
    """Current time on the recorder's wall-clock axis (monotonic clock
    anchored to the epoch once at import).  Use this — not time.time() —
    to stamp p2p envelopes: an NTP step mid-run cannot make two stamps
    from the same process go backwards."""
    return _EPOCH_T0 + time.perf_counter()


def default_category(name: str) -> str | None:
    """Category inferred from a span name, or None when no rule matches
    (uncategorized spans simply don't participate in attribution)."""
    for prefix, cat in _CAT_BY_PREFIX:
        if name.startswith(prefix):
            return cat
    return None


class FlightRecorder:
    """Fixed-capacity ring of span records, oldest overwritten first.

    A record is the tuple (name, ph, ts_s, dur_s, tid, tname, cat, lane,
    args): wall-clock start, monotonic duration, originating thread,
    attribution category, and lane (the logical thread/stream the work
    ran on — defaults to the recording thread's name).  Tuples (not
    dicts) keep the hot-path allocation to one object."""

    def __init__(self, capacity: int = 16384):
        if capacity < 1:
            raise ValueError("flight recorder capacity must be >= 1")
        self.capacity = capacity
        self._buf: list = [None] * capacity
        self._head = 0                    # next write slot
        self._total = 0                   # spans ever recorded
        self._lock = threading.Lock()

    # -- recording -------------------------------------------------------
    def record(self, name: str, ts_s: float, dur_s: float,
               args: dict | None = None, ph: str = PH_SPAN,
               cat: str | None = None, lane: str | None = None) -> None:
        t = threading.current_thread()
        if cat is None:
            cat = default_category(name)
        rec = (name, ph, ts_s, dur_s, t.ident or 0, t.name, cat,
               lane or t.name, args or None)
        with self._lock:
            self._buf[self._head] = rec
            self._head = (self._head + 1) % self.capacity
            self._total += 1

    @contextmanager
    def span(self, name: str, cat: str | None = None,
             lane: str | None = None, **args):
        """Time a block; the span is recorded even when the block raises
        (a span that vanishes on failure hides exactly the interesting
        case), with error=<type> appended to its args.  `cat` and `lane`
        are reserved keywords feeding the attribution profiler; every
        other keyword lands in the span's args."""
        p0 = time.perf_counter()
        try:
            yield
        except BaseException as e:
            args = {**args, "error": type(e).__name__}
            raise
        finally:
            self.record(name, _EPOCH_T0 + p0, time.perf_counter() - p0,
                        args, cat=cat, lane=lane)

    def instant(self, name: str, **args) -> None:
        self.record(name, _EPOCH_T0 + time.perf_counter(), 0.0, args,
                    ph=PH_INSTANT)

    # -- reading ---------------------------------------------------------
    def snapshot(self) -> list[dict]:
        """Spans oldest-first as dicts (RPC / CLI serialization form)."""
        with self._lock:
            if self._total >= self.capacity:
                recs = self._buf[self._head:] + self._buf[:self._head]
            else:
                recs = self._buf[:self._head]
        return [{"name": n, "ph": ph, "ts": ts, "dur": dur,
                 "tid": tid, "thread": tname, "lane": lane,
                 **({"cat": cat} if cat else {}),
                 **({"args": args} if args else {})}
                for rec in recs if rec is not None
                for (n, ph, ts, dur, tid, tname, cat, lane, args)
                in (rec,)]

    def last(self, name: str) -> dict | None:
        """Most recent span with `name` (bench's budget manager reads the
        last fixture-build cost here), or None."""
        for rec in reversed(self.snapshot()):
            if rec["name"] == name:
                return rec
        return None

    @property
    def total(self) -> int:
        return self._total

    @property
    def dropped(self) -> int:
        return max(0, self._total - self.capacity)

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self.capacity
            self._head = 0
            self._total = 0

    # -- export ----------------------------------------------------------
    def to_chrome_trace(self) -> dict:
        """Chrome trace-event JSON (the format Perfetto, chrome://tracing
        and TensorBoard's trace viewer load): one "X" complete event per
        span (ts/dur in MICROseconds), "i" instants, plus one "M"
        thread_name metadata event per thread seen."""
        pid = os.getpid()
        events = []
        threads: dict[int, str] = {}
        for rec in self.snapshot():
            tid = rec["tid"]
            threads.setdefault(tid, rec["thread"])
            ev = {"name": rec["name"], "ph": rec["ph"], "pid": pid,
                  "tid": tid, "ts": rec["ts"] * 1e6}
            if "cat" in rec:
                ev["cat"] = rec["cat"]
            if rec["ph"] == PH_SPAN:
                ev["dur"] = rec["dur"] * 1e6
            else:
                ev["s"] = "t"            # instant scope: thread
            if "args" in rec:
                ev["args"] = rec["args"]
            events.append(ev)
        for tid, tname in threads.items():
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": tname}})
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"recorder_total": self._total,
                              "recorder_dropped": self.dropped}}

    def dump(self, path: str) -> str:
        """Atomically write the Chrome trace JSON to `path` (tmp +
        rename: a dump interrupted by the very signal that triggered it
        must not leave a truncated file)."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path


RECORDER = FlightRecorder(
    int(os.environ.get("TM_FLIGHT_RECORDER_CAP", "16384")))

span = RECORDER.span
instant = RECORDER.instant
