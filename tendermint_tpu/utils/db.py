"""Key-value store abstraction: memdb and a durable sqlite backend.

Reference: tmlibs/db (goleveldb / memdb, selected by `DBBackend`,
`config/config.go:102,121`).  sqlite3 is the stdlib-native durable engine
here — single-writer, WAL-journaled, crash-safe, zero install — used for
the block store, state store, and tx index.
"""

from __future__ import annotations

import sqlite3
import threading


class MemDB:
    """In-memory store (reference memdb): tests and throwaway nodes."""

    def __init__(self):
        self._d: dict[bytes, bytes] = {}
        self._lock = threading.Lock()

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self._d.get(key)

    def set(self, key: bytes, value: bytes) -> None:
        with self._lock:
            self._d[key] = value

    def set_batch(self, kvs: list[tuple[bytes, bytes]]) -> None:
        with self._lock:
            self._d.update(kvs)

    def delete(self, key: bytes) -> None:
        with self._lock:
            self._d.pop(key, None)

    def iterate_prefix(self, prefix: bytes):
        with self._lock:
            items = [(k, v) for k, v in self._d.items()
                     if k.startswith(prefix)]
        return sorted(items)

    def close(self) -> None:
        pass


class SQLiteDB:
    """Durable store: one `kv` table, WAL mode, synchronous=NORMAL."""

    def __init__(self, path: str):
        self.path = path
        self._local = threading.local()
        conn = self._conn()
        conn.execute("CREATE TABLE IF NOT EXISTS kv "
                     "(k BLOB PRIMARY KEY, v BLOB NOT NULL)")
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        conn.commit()

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path)
            self._local.conn = conn
        return conn

    def get(self, key: bytes) -> bytes | None:
        row = self._conn().execute("SELECT v FROM kv WHERE k=?",
                                   (key,)).fetchone()
        return row[0] if row else None

    def set(self, key: bytes, value: bytes) -> None:
        conn = self._conn()
        conn.execute("INSERT OR REPLACE INTO kv VALUES (?,?)", (key, value))
        conn.commit()

    def set_batch(self, kvs: list[tuple[bytes, bytes]]) -> None:
        conn = self._conn()
        conn.executemany("INSERT OR REPLACE INTO kv VALUES (?,?)", kvs)
        conn.commit()

    def delete(self, key: bytes) -> None:
        conn = self._conn()
        conn.execute("DELETE FROM kv WHERE k=?", (key,))
        conn.commit()

    def iterate_prefix(self, prefix: bytes):
        hi = _prefix_upper_bound(prefix)
        if hi is None:   # prefix is all 0xff (or empty): no upper bound
            return self._conn().execute(
                "SELECT k, v FROM kv WHERE k >= ? ORDER BY k",
                (prefix,)).fetchall()
        return self._conn().execute(
            "SELECT k, v FROM kv WHERE k >= ? AND k < ? ORDER BY k",
            (prefix, hi)).fetchall()

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None


def _prefix_upper_bound(prefix: bytes) -> bytes | None:
    """Smallest byte string greater than every key with this prefix."""
    p = bytearray(prefix)
    while p and p[-1] == 0xFF:
        p.pop()
    if not p:
        return None
    p[-1] += 1
    return bytes(p)


def new_db(backend: str, path: str | None = None):
    """Factory (reference `config/config.go:102` DBBackend)."""
    if backend == "memdb":
        return MemDB()
    if backend == "sqlite":
        assert path, "sqlite backend needs a path"
        return SQLiteDB(path)
    raise ValueError(f"unknown db backend {backend!r}")
