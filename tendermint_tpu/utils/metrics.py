"""First-class runtime metrics — "the benchmark currency" (SURVEY.md §5).

The reference's observability is events + RPC snapshots; this framework
additionally counts the quantities its design is judged on: blocks
committed/s, signatures verified/s, verify-batch occupancy (how full the
padded device batches run), and device step latency.

Global registry, lock-per-instrument, exposed as one dict via
`snapshot()` for the `status` / `dump_consensus_state` RPC routes and for
bench harnesses.
"""

from __future__ import annotations

import threading
import time


class Counter:
    __slots__ = ("_v", "_lock")

    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v


class Gauge:
    __slots__ = ("_v", "_lock")

    def __init__(self):
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    @property
    def value(self) -> float:
        return self._v


class Summary:
    """Streaming mean/min/max with exponential decay toward recent
    samples.  `min` matters for breakeven decisions: the first sample of
    a device-call summary includes the XLA compile, so the mean starts
    wildly inflated while the min converges to the steady per-call cost
    after one warm call."""
    __slots__ = ("_mean", "_min", "_max", "_n", "_lock", "alpha")

    def __init__(self, alpha: float = 0.1):
        self._mean = 0.0
        self._min = 0.0
        self._max = 0.0
        self._n = 0
        self._lock = threading.Lock()
        self.alpha = alpha

    def observe(self, v: float) -> None:
        with self._lock:
            self._n += 1
            if self._n == 1:
                self._mean = v
                self._min = v
            else:
                self._mean += self.alpha * (v - self._mean)
                if v < self._min:
                    self._min = v
            if v > self._max:
                self._max = v

    @property
    def mean(self) -> float:
        return self._mean

    @property
    def count(self) -> int:
        return self._n

    @property
    def min(self) -> float:
        return self._min


class Registry:
    def __init__(self):
        self._start = time.time()
        # consensus plane
        self.blocks_committed = Counter()
        self.txs_committed = Counter()
        self.rounds_started = Counter()
        # crypto plane
        self.sigs_verified = Counter()        # signatures that PASSED
        self.sigs_requested = Counter()       # real signatures asked for
        self.verify_batches = Counter()
        self.batch_occupancy = Summary()      # real/padded per batch
        self.device_step_seconds = Summary()  # wait-for-result per call
        self.device_dispatch_seconds = Summary()  # dispatch->result wall
        #   (includes overlapped host work in pipelined callers)
        self.table_build_seconds = Summary()  # comb-table builds (per set)
        # supervised-crypto plane (crypto/supervised.py)
        self.crypto_device_faults = Counter()   # faults seen on any rung
        self.crypto_fallback_calls = Counter()  # calls served below rung 0
        self.crypto_breaker_trips = Counter()   # CLOSED/HALF-OPEN -> OPEN
        self.crypto_breaker_recoveries = Counter()  # HALF-OPEN -> CLOSED
        self.crypto_spot_checks = Counter()
        self.crypto_spot_check_mismatches = Counter()
        # live-vote micro-batching (receive-loop burst ingestion)
        self.vote_microbatches = Counter()
        self.vote_microbatch_lanes = Counter()
        # sync plane
        self.blocks_synced = Counter()
        # p2p plane
        self.peers = Gauge()
        self.msgs_sent = Counter()
        self.msgs_received = Counter()

    def snapshot(self) -> dict:
        up = max(time.time() - self._start, 1e-9)
        return {
            "uptime_seconds": round(up, 1),
            "blocks_committed": self.blocks_committed.value,
            "blocks_per_sec": round(self.blocks_committed.value / up, 3),
            "txs_committed": self.txs_committed.value,
            "rounds_started": self.rounds_started.value,
            "sigs_requested": self.sigs_requested.value,
            "sigs_verified": self.sigs_verified.value,
            "sigs_per_sec": round(self.sigs_requested.value / up, 1),
            "verify_batches": self.verify_batches.value,
            "batch_occupancy_mean": round(self.batch_occupancy.mean, 4),
            "device_step_seconds_mean":
                round(self.device_step_seconds.mean, 6),
            "device_dispatch_seconds_mean":
                round(self.device_dispatch_seconds.mean, 6),
            "crypto_device_faults": self.crypto_device_faults.value,
            "crypto_fallback_calls": self.crypto_fallback_calls.value,
            "crypto_breaker_trips": self.crypto_breaker_trips.value,
            "crypto_breaker_recoveries":
                self.crypto_breaker_recoveries.value,
            "crypto_spot_checks": self.crypto_spot_checks.value,
            "crypto_spot_check_mismatches":
                self.crypto_spot_check_mismatches.value,
            "vote_microbatches": self.vote_microbatches.value,
            "vote_microbatch_lanes": self.vote_microbatch_lanes.value,
            "blocks_synced": self.blocks_synced.value,
            "peers": self.peers.value,
            "p2p_msgs_sent": self.msgs_sent.value,
            "p2p_msgs_received": self.msgs_received.value,
        }


REGISTRY = Registry()


def snapshot() -> dict:
    return REGISTRY.snapshot()
