"""First-class runtime metrics — "the benchmark currency" (SURVEY.md §5).

The reference's observability is events + RPC snapshots; this framework
additionally counts the quantities its design is judged on: blocks
committed/s, signatures verified/s, verify-batch occupancy (how full the
padded device batches run), and device step latency.

Global registry, lock-per-instrument, exposed as one dict via
`snapshot()` for the `status` / `dump_consensus_state` RPC routes and for
bench harnesses.
"""

from __future__ import annotations

import threading
import time


class Counter:
    __slots__ = ("_v", "_lock")

    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v


class Gauge:
    __slots__ = ("_v", "_lock")

    def __init__(self):
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    @property
    def value(self) -> float:
        return self._v


class Summary:
    """Streaming mean/min/max with exponential decay toward recent
    samples.  `min` matters for breakeven decisions: the first sample of
    a device-call summary includes the XLA compile, so the mean starts
    wildly inflated while the min converges to the steady per-call cost
    after one warm call."""
    __slots__ = ("_mean", "_min", "_max", "_n", "_lock", "alpha")

    def __init__(self, alpha: float = 0.1):
        self._mean = 0.0
        self._min = 0.0
        self._max = 0.0
        self._n = 0
        self._lock = threading.Lock()
        self.alpha = alpha

    def observe(self, v: float) -> None:
        with self._lock:
            self._n += 1
            if self._n == 1:
                self._mean = v
                self._min = v
            else:
                self._mean += self.alpha * (v - self._mean)
                if v < self._min:
                    self._min = v
            if v > self._max:
                self._max = v

    @property
    def mean(self) -> float:
        return self._mean

    @property
    def count(self) -> int:
        return self._n

    @property
    def min(self) -> float:
        return self._min


class Histogram:
    """Fixed-bucket histogram: counts per upper bound plus sum/count, the
    Prometheus histogram layout.  Unlike `Summary` (decayed mean — good
    for steering heuristics, blind to tails) this answers the questions a
    benchmark scoreboard asks: p50/p90/p99 device step latency, batch
    occupancy distribution, round duration spread.  Quantiles are the
    standard bucket interpolation — exact bucket, linear within it."""

    # latency bounds (seconds): 100us .. 10s, the device-call range
    LATENCY_BOUNDS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                      0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                      10.0)
    # ratio bounds: batch occupancy lives in (0, 1]
    RATIO_BOUNDS = (0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9,
                    0.95, 1.0)
    # wall-clock bounds (seconds): consensus round durations
    DURATION_BOUNDS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                       10.0, 30.0, 60.0)

    __slots__ = ("bounds", "_counts", "_sum", "_n", "_lock")

    def __init__(self, bounds=LATENCY_BOUNDS):
        self.bounds = tuple(bounds)
        if list(self.bounds) != sorted(self.bounds) or not self.bounds:
            raise ValueError("histogram bounds must be sorted, non-empty")
        self._counts = [0] * (len(self.bounds) + 1)   # +1 = +Inf overflow
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        i = 0
        for i, b in enumerate(self.bounds):
            if v <= b:
                break
        else:
            i = len(self.bounds)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._n += 1

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum

    def buckets(self) -> list[tuple[float, int]]:
        """(upper_bound, CUMULATIVE count) per bucket, +Inf last — the
        exposition-format shape."""
        with self._lock:
            counts = list(self._counts)
        out, cum = [], 0
        for b, c in zip(self.bounds, counts):
            cum += c
            out.append((b, cum))
        out.append((float("inf"), cum + counts[-1]))
        return out

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0 < q <= 1).  0.0 when empty; values in
        the overflow bucket report the highest finite bound (the same
        saturation Prometheus' histogram_quantile applies)."""
        with self._lock:
            counts = list(self._counts)
            n = self._n
        if n == 0:
            return 0.0
        target = q * n
        cum = 0
        lo = 0.0
        for b, c in zip(self.bounds, counts):
            if cum + c >= target and c > 0:
                return lo + (b - lo) * (target - cum) / c
            cum += c
            lo = b
        return self.bounds[-1]

    def snapshot(self) -> dict:
        return {"count": self.count, "sum": round(self._sum, 6),
                "p50": round(self.quantile(0.50), 6),
                "p90": round(self.quantile(0.90), 6),
                "p99": round(self.quantile(0.99), 6)}


class CounterVec:
    """A counter family keyed by one label (e.g. the crypto ladder rung):
    `vec.labels("tpu").inc()`.  Cells are created on first touch so a
    scrape sees exactly the rungs that have served calls — a demotion to
    `native` appears as a new labeled series the moment it happens."""

    __slots__ = ("label", "_cells", "_lock")

    def __init__(self, label: str):
        self.label = label
        self._cells: dict[str, Counter] = {}
        self._lock = threading.Lock()

    def labels(self, value: str) -> Counter:
        with self._lock:
            c = self._cells.get(value)
            if c is None:
                c = self._cells[value] = Counter()
            return c

    def items(self) -> list[tuple[str, int]]:
        with self._lock:
            return [(k, c.value) for k, c in sorted(self._cells.items())]


class GaugeVec:
    """A gauge family keyed by one label — per-device utilization gauges
    (`vec.labels("tpu:0").set(0.92)`) without pre-declaring the device
    list."""

    __slots__ = ("label", "_cells", "_lock")

    def __init__(self, label: str):
        self.label = label
        self._cells: dict[str, Gauge] = {}
        self._lock = threading.Lock()

    def labels(self, value: str) -> Gauge:
        with self._lock:
            g = self._cells.get(value)
            if g is None:
                g = self._cells[value] = Gauge()
            return g

    def items(self) -> list[tuple[str, float]]:
        with self._lock:
            return [(k, g.value) for k, g in sorted(self._cells.items())]


class HistogramVec:
    """A histogram family keyed by one label — per-class batch-plane
    queue-wait distributions (`vec.labels("consensus").observe(dt)`)
    without pre-declaring the class list.  Renders as one labeled
    _bucket/_sum/_count triple per cell."""

    __slots__ = ("label", "bounds", "_cells", "_lock")

    def __init__(self, label: str, bounds=Histogram.LATENCY_BOUNDS):
        self.label = label
        self.bounds = tuple(bounds)
        self._cells: dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def labels(self, value: str) -> Histogram:
        with self._lock:
            h = self._cells.get(value)
            if h is None:
                h = self._cells[value] = Histogram(self.bounds)
            return h

    def items(self) -> list[tuple[str, Histogram]]:
        with self._lock:
            return sorted(self._cells.items())

    def snapshot(self) -> dict:
        return {k: h.snapshot() for k, h in self.items()}


class Registry:
    def __init__(self):
        self._start = time.time()
        # consensus plane
        self.blocks_committed = Counter()
        self.txs_committed = Counter()
        self.rounds_started = Counter()
        # crypto plane
        self.sigs_verified = Counter()        # signatures that PASSED
        self.sigs_requested = Counter()       # real signatures asked for
        self.verify_batches = Counter()
        self.batch_occupancy = Summary()      # real/padded per batch
        self.device_step_seconds = Summary()  # wait-for-result per call
        self.device_dispatch_seconds = Summary()  # dispatch->result wall
        #   (includes overlapped host work in pipelined callers)
        self.table_build_seconds = Summary()  # comb-table builds (per set)
        # tail-aware distributions (the Summary twins above keep the
        # steering heuristics; these feed the /metrics scrape + p99s)
        self.device_step_hist = Histogram(Histogram.LATENCY_BOUNDS)
        self.batch_occupancy_hist = Histogram(Histogram.RATIO_BOUNDS)
        self.round_seconds_hist = Histogram(Histogram.DURATION_BOUNDS)
        # supervised-crypto plane (crypto/supervised.py)
        self.crypto_device_faults = Counter()   # faults seen on any rung
        self.crypto_fallback_calls = Counter()  # calls served below rung 0
        self.crypto_breaker_trips = Counter()   # CLOSED/HALF-OPEN -> OPEN
        self.crypto_breaker_recoveries = Counter()  # HALF-OPEN -> CLOSED
        self.crypto_spot_checks = Counter()
        self.crypto_spot_check_mismatches = Counter()
        # per-rung call/fault counts, labeled by ladder rung
        # (tpu/native/python): a SupervisedBackend demotion shows up on a
        # scrape as the lower rung's calls series starting to move
        self.crypto_rung_calls = CounterVec("rung")
        self.crypto_rung_faults = CounterVec("rung")
        # live-vote micro-batching (receive-loop burst ingestion)
        self.vote_microbatches = Counter()
        self.vote_microbatch_lanes = Counter()
        # sync plane
        self.blocks_synced = Counter()
        # state-sync / snapshot plane (statesync/): chunks_verified vs
        # chunks_rejected is the no-silent-acceptance ledger — every
        # fetched chunk lands in exactly one of the two, and a rejected
        # chunk always carries a peer blame on the switch
        self.snapshots_created = Counter()
        self.snapshot_create_seconds = Summary()
        self.snapshot_restore_seconds = Summary()
        self.chunks_verified = Counter()
        self.chunks_rejected = Counter()
        self.restore_replay_blocks = Counter()  # snapshot_height -> tip
        # p2p plane
        self.peers = Gauge()
        self.msgs_sent = Counter()
        self.msgs_received = Counter()
        # p2p self-healing plane (p2p/switch.py): reconnect attempts are
        # the graceful-degradation signal under partitions (a heal storm
        # shows as a burst, a dead peer as a bounded trickle); evictions
        # count misbehavior-score bans, never plain connection deaths
        self.switch_reconnect_attempts = Counter()
        self.switch_peers_evicted = Counter()
        # XLA compile/cache plane (crypto/backend.py instrumentation):
        # first-call compiles are the 100-160s tax the warm cache exists
        # to kill; a recompile on a warm entry means SHAPE DRIFT — the
        # bucketing in crypto/backend._bucket() leaked a new padded shape
        self.xla_compiles = Counter()           # real backend compiles
        self.xla_compile_seconds = Summary()    # per-compile duration
        self.xla_first_call_seconds = Summary()  # first dispatch per entry
        self.xla_cache_hits = Counter()         # dispatch on a warm shape
        self.xla_cache_misses = Counter()       # dispatch on a cold shape
        self.xla_recompiles = Counter()         # new shape on a warm entry
        # host<->device transfer plane
        self.h2d_bytes = Counter()
        self.d2h_bytes = Counter()
        # per-device plane (parallel/sharding.py multi-device runs)
        self.device_util = GaugeVec("device")    # busy fraction per device
        self.device_lanes = CounterVec("device")  # lanes served per device
        # pipeline attribution plane (utils/attribution.py per-window
        # partition of replay wall clock)
        self.window_overlap_frac_hist = Histogram(Histogram.RATIO_BOUNDS)
        self.window_device_busy_frac_hist = Histogram(
            Histogram.RATIO_BOUNDS)
        self.window_device_idle_frac_hist = Histogram(
            Histogram.RATIO_BOUNDS)
        self.window_scalar_seconds = Histogram(Histogram.DURATION_BOUNDS)
        # bench regression ledger (utils/ledger.py): worst per-config
        # delta_frac of the latest run vs best prior (negative = slower);
        # alert on < -threshold
        self.bench_regression = Gauge()
        # unified batch plane (batchplane/scheduler.py): the coalescing
        # proof lives here — occupancy is real lanes over the padded
        # chunk a flush rode, mixed_batches counts flushes whose lanes
        # came from >1 producer, and the per-class wait histogram is
        # the latency cost each class paid to coalesce
        self.batchplane_flushes = Counter()
        self.batchplane_mixed_batches = Counter()
        self.batchplane_flush_reason = CounterVec("reason")
        self.batchplane_lanes = CounterVec("producer")
        self.batchplane_occupancy_hist = Histogram(Histogram.RATIO_BOUNDS)
        self.batchplane_queue_depth_hist = Histogram(
            (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024))
        self.batchplane_wait_seconds = HistogramVec(
            "klass", Histogram.LATENCY_BOUNDS)
        # mempool ingress plane (mempool/mempool.py admission
        # controller): every submission lands in exactly one outcome —
        # admitted into the pool or counted in rejected{reason} — and
        # every eviction in evicted{reason}; that accounting identity
        # is the zero-silent-drops invariant the eviction-storm
        # scenario audits.  admit_seconds is the per-submission
        # admission latency (dup/full rejects included) whose p50/p99
        # the mempool-flood gate budgets.
        self.mempool_size = Gauge()
        self.mempool_bytes = Gauge()
        self.mempool_rejected = CounterVec("reason")
        self.mempool_evicted = CounterVec("reason")
        self.mempool_admit_seconds = Histogram(Histogram.LATENCY_BOUNDS)
        # consensus timeline plane (telemetry/): per-stage height
        # lifecycle durations (propose / prevote / precommit / commit —
        # the four stages partition each height's wall clock, same
        # sums-to-wall invariant as utils/attribution.py), gossip
        # fan-out lag (origin send-stamp -> ingest at the receiver),
        # batchplane verify wait attributable to vote ingest, and a
        # per-node last-committed-height gauge fed by the mesh
        # collector (node ids are hostname-shaped: dashes/dots).
        self.consensus_stage_seconds = HistogramVec(
            "stage", Histogram.DURATION_BOUNDS)
        self.consensus_height_seconds = Histogram(
            Histogram.DURATION_BOUNDS)
        self.gossip_fanout_seconds = Histogram(Histogram.LATENCY_BOUNDS)
        self.timeline_node_height = GaugeVec("node")

    def snapshot(self) -> dict:
        up = max(time.time() - self._start, 1e-9)
        return {
            "uptime_seconds": round(up, 1),
            "blocks_committed": self.blocks_committed.value,
            "blocks_per_sec": round(self.blocks_committed.value / up, 3),
            "txs_committed": self.txs_committed.value,
            "rounds_started": self.rounds_started.value,
            "sigs_requested": self.sigs_requested.value,
            "sigs_verified": self.sigs_verified.value,
            "sigs_per_sec": round(self.sigs_requested.value / up, 1),
            "verify_batches": self.verify_batches.value,
            "batch_occupancy_mean": round(self.batch_occupancy.mean, 4),
            "device_step_seconds_mean":
                round(self.device_step_seconds.mean, 6),
            "device_dispatch_seconds_mean":
                round(self.device_dispatch_seconds.mean, 6),
            "crypto_device_faults": self.crypto_device_faults.value,
            "crypto_fallback_calls": self.crypto_fallback_calls.value,
            "crypto_breaker_trips": self.crypto_breaker_trips.value,
            "crypto_breaker_recoveries":
                self.crypto_breaker_recoveries.value,
            "crypto_spot_checks": self.crypto_spot_checks.value,
            "crypto_spot_check_mismatches":
                self.crypto_spot_check_mismatches.value,
            "vote_microbatches": self.vote_microbatches.value,
            "vote_microbatch_lanes": self.vote_microbatch_lanes.value,
            "blocks_synced": self.blocks_synced.value,
            "snapshots_created": self.snapshots_created.value,
            "snapshot_create_seconds_mean":
                round(self.snapshot_create_seconds.mean, 6),
            "snapshot_restore_seconds_mean":
                round(self.snapshot_restore_seconds.mean, 6),
            "chunks_verified": self.chunks_verified.value,
            "chunks_rejected": self.chunks_rejected.value,
            "restore_replay_blocks": self.restore_replay_blocks.value,
            "peers": self.peers.value,
            "p2p_msgs_sent": self.msgs_sent.value,
            "p2p_msgs_received": self.msgs_received.value,
            "switch_reconnect_attempts":
                self.switch_reconnect_attempts.value,
            "switch_peers_evicted": self.switch_peers_evicted.value,
            "device_step_seconds": self.device_step_hist.snapshot(),
            "batch_occupancy": self.batch_occupancy_hist.snapshot(),
            "round_seconds": self.round_seconds_hist.snapshot(),
            "crypto_rung_calls": dict(self.crypto_rung_calls.items()),
            "crypto_rung_faults": dict(self.crypto_rung_faults.items()),
            "xla_compiles": self.xla_compiles.value,
            "xla_compile_seconds_mean":
                round(self.xla_compile_seconds.mean, 3),
            "xla_cache_hits": self.xla_cache_hits.value,
            "xla_cache_misses": self.xla_cache_misses.value,
            "xla_recompiles": self.xla_recompiles.value,
            "h2d_bytes": self.h2d_bytes.value,
            "d2h_bytes": self.d2h_bytes.value,
            "device_util": dict(self.device_util.items()),
            "bench_regression": self.bench_regression.value,
            "batchplane_flushes": self.batchplane_flushes.value,
            "batchplane_mixed_batches":
                self.batchplane_mixed_batches.value,
            "batchplane_flush_reason":
                dict(self.batchplane_flush_reason.items()),
            "batchplane_lanes": dict(self.batchplane_lanes.items()),
            "batchplane_occupancy":
                self.batchplane_occupancy_hist.snapshot(),
            "batchplane_queue_depth":
                self.batchplane_queue_depth_hist.snapshot(),
            "batchplane_wait_seconds":
                self.batchplane_wait_seconds.snapshot(),
            "mempool_size": self.mempool_size.value,
            "mempool_bytes": self.mempool_bytes.value,
            "mempool_rejected": dict(self.mempool_rejected.items()),
            "mempool_evicted": dict(self.mempool_evicted.items()),
            "mempool_admit_seconds":
                self.mempool_admit_seconds.snapshot(),
            "consensus_stage_seconds":
                self.consensus_stage_seconds.snapshot(),
            "consensus_height_seconds":
                self.consensus_height_seconds.snapshot(),
            "gossip_fanout_seconds":
                self.gossip_fanout_seconds.snapshot(),
            "timeline_node_height": dict(self.timeline_node_height.items()),
        }


REGISTRY = Registry()


def snapshot() -> dict:
    return REGISTRY.snapshot()


# -- Prometheus text exposition (format version 0.0.4) ----------------------

_PROM_PREFIX = "tendermint_"

# wall-clock process start, exported as the standard (unprefixed)
# `process_start_time_seconds` so Prometheus' `time() - ...` uptime
# recipes and restart detection work against this exporter
_PROCESS_START = time.time()

# build_info labels, populated by set_build_info() as subsystems learn
# facts about themselves (crypto backend init fills in the jax backend
# and device count); rendered as the conventional value-1 info gauge
_BUILD_INFO: dict[str, str] = {}
_BUILD_INFO_LOCK = threading.Lock()


def set_build_info(**labels) -> None:
    """Merge label->value pairs into the build_info gauge (values are
    stringified; None values are skipped)."""
    with _BUILD_INFO_LOCK:
        for k, v in labels.items():
            if v is not None:
                _BUILD_INFO[k] = str(v)


try:
    from tendermint_tpu import __version__ as _VERSION
except Exception:                                    # pragma: no cover
    _VERSION = "unknown"
set_build_info(version=_VERSION)


def _prom_f(v: float) -> str:
    """Prometheus float rendering: +Inf spelled out, no exponent noise."""
    if v == float("inf"):
        return "+Inf"
    return repr(float(v)) if isinstance(v, float) else str(v)


def _prom_escape(v: str) -> str:
    """Label-VALUE escaping per the 0.0.4 text format: backslash, double
    quote and line feed must be escaped inside the quotes — an unescaped
    newline in a label value splits the line and corrupts the whole
    scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def prometheus_text(registry: Registry | None = None) -> str:
    """The whole registry in the Prometheus text exposition format,
    served at GET /metrics by the RPC server.  Instruments map by type:
    Counter -> counter, Gauge/Summary -> gauge(s), Histogram -> the
    _bucket{le=}/_sum/_count triple, CounterVec -> one labeled series
    per cell."""
    r = registry if registry is not None else REGISTRY
    lines: list[str] = []
    for attr, inst in vars(r).items():
        if attr.startswith("_"):
            continue
        name = _PROM_PREFIX + attr
        if isinstance(inst, Counter):
            lines += [f"# TYPE {name} counter", f"{name} {inst.value}"]
        elif isinstance(inst, Gauge):
            lines += [f"# TYPE {name} gauge", f"{name} {_prom_f(inst.value)}"]
        elif isinstance(inst, Summary):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{{stat=\"mean\"}} {_prom_f(inst.mean)}")
            lines.append(f"{name}{{stat=\"min\"}} {_prom_f(inst.min)}")
            lines.append(f"{name}_count {inst.count}")
        elif isinstance(inst, Histogram):
            lines.append(f"# TYPE {name} histogram")
            for le, cum in inst.buckets():
                lines.append(f"{name}_bucket{{le=\"{_prom_f(le)}\"}} {cum}")
            lines.append(f"{name}_sum {_prom_f(inst.sum)}")
            lines.append(f"{name}_count {inst.count}")
        elif isinstance(inst, CounterVec):
            lines.append(f"# TYPE {name} counter")
            for label_value, v in inst.items():
                lines.append(
                    f"{name}{{{inst.label}=\"{_prom_escape(label_value)}\"}}"
                    f" {v}")
        elif isinstance(inst, GaugeVec):
            lines.append(f"# TYPE {name} gauge")
            for label_value, v in inst.items():
                lines.append(
                    f"{name}{{{inst.label}=\"{_prom_escape(label_value)}\"}}"
                    f" {_prom_f(v)}")
        elif isinstance(inst, HistogramVec):
            lines.append(f"# TYPE {name} histogram")
            for label_value, h in inst.items():
                lv = _prom_escape(label_value)
                for le, cum in h.buckets():
                    lines.append(
                        f"{name}_bucket{{{inst.label}=\"{lv}\","
                        f"le=\"{_prom_f(le)}\"}} {cum}")
                lines.append(
                    f"{name}_sum{{{inst.label}=\"{lv}\"}} "
                    f"{_prom_f(h.sum)}")
                lines.append(
                    f"{name}_count{{{inst.label}=\"{lv}\"}} {h.count}")
    lines.append(f"# TYPE {_PROM_PREFIX}uptime_seconds gauge")
    lines.append(f"{_PROM_PREFIX}uptime_seconds "
                 f"{_prom_f(round(time.time() - r._start, 3))}")
    # standard process metric (unprefixed by convention): lets the usual
    # restart-detection and uptime recording rules work unmodified
    lines.append("# TYPE process_start_time_seconds gauge")
    lines.append(f"process_start_time_seconds {_prom_f(_PROCESS_START)}")
    with _BUILD_INFO_LOCK:
        info = dict(_BUILD_INFO)
    labels = ",".join(f'{k}="{_prom_escape(v)}"'
                      for k, v in sorted(info.items()))
    lines.append(f"# TYPE {_PROM_PREFIX}build_info gauge")
    lines.append(f"{_PROM_PREFIX}build_info{{{labels}}} 1")
    return "\n".join(lines) + "\n"
