"""Pipeline attribution: where did the wall clock go?

The flight recorder (utils/tracing.py) answers "what spans ran"; this
module answers the scoreboard's question — for a replay window (or a
whole bench run), how much wall clock was XLA compile, host<->device
transfer, device-busy compute, scalar-fallback crypto, and how much was
the device simply sitting IDLE.  Blockchain Machine (arXiv:2104.06968)
treats per-stage rate instrumentation as a first-class contribution of a
hardware BFT pipeline; this is that layer for the jax_graft hot path.

The accounting is a *priority partition*: every instant of a window is
attributed to exactly one category, highest priority first

    compile > transfer > device > scalar > idle

so the components always sum to the window's wall clock (the acceptance
bar: within 10% — here it holds to float rounding, by construction).
An instant covered by both a compile span and a device span counts as
compile: when the executable is being built, the device time underneath
is not productive verify throughput.

Overlap fraction is reported separately: the share of the window where
at least two of the prep / device / apply stages ran concurrently — 1.0
means a perfectly pipelined window, 0.0 a fully serial one (the round-5
failure shape: prep, verify, apply each running alone).

All functions take the span-dict form `FlightRecorder.snapshot()`
returns; none of them import jax, so the doctor runs on a dump from any
host.
"""

from __future__ import annotations

from tendermint_tpu.utils import tracing

# priority order of the exclusive partition (idle = remainder)
PARTITION = (tracing.CAT_COMPILE, tracing.CAT_TRANSFER,
             tracing.CAT_DEVICE, tracing.CAT_SCALAR)

# report keys for the partition, in the same order
_REPORT_KEY = {tracing.CAT_COMPILE: "compile",
               tracing.CAT_TRANSFER: "transfer",
               tracing.CAT_DEVICE: "device_busy",
               tracing.CAT_SCALAR: "scalar_tail"}

DOCTOR_SCHEMA = "tpu-bft-doctor/1"


# ---------------------------------------------------------------------------
# interval arithmetic — closed-open [start, end) second intervals
# ---------------------------------------------------------------------------

def merge(intervals) -> list[tuple[float, float]]:
    """Union of intervals as a sorted, disjoint list."""
    ivs = sorted((s, e) for s, e in intervals if e > s)
    out: list[tuple[float, float]] = []
    for s, e in ivs:
        if out and s <= out[-1][1]:
            if e > out[-1][1]:
                out[-1] = (out[-1][0], e)
        else:
            out.append((s, e))
    return out


def total(intervals) -> float:
    return sum(e - s for s, e in intervals)


def clip(intervals, lo: float, hi: float) -> list[tuple[float, float]]:
    """Intervals intersected with the window [lo, hi)."""
    return [(max(s, lo), min(e, hi)) for s, e in intervals
            if min(e, hi) > max(s, lo)]


def subtract(a, b) -> list[tuple[float, float]]:
    """a minus b; both merged-disjoint, result merged-disjoint."""
    out = []
    bi = list(b)
    for s, e in a:
        cur = s
        for bs, be in bi:
            if be <= cur or bs >= e:
                continue
            if bs > cur:
                out.append((cur, bs))
            cur = max(cur, be)
            if cur >= e:
                break
        if cur < e:
            out.append((cur, e))
    return out


def intersect(a, b) -> list[tuple[float, float]]:
    """a intersect b; both merged-disjoint."""
    out, i, j = [], 0, 0
    a, b = list(a), list(b)
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            out.append((s, e))
        if a[i][1] < b[j][1]:
            i += 1
        else:
            j += 1
    return out


def covered_by_at_least(interval_lists, k: int) -> list[tuple[float, float]]:
    """Region covered by >= k of the given (merged) interval lists —
    boundary sweep over all edges.  Used for the pipeline overlap
    fraction (k=2 over prep/device/apply)."""
    edges = []
    for ivs in interval_lists:
        for s, e in ivs:
            edges.append((s, 1))
            edges.append((e, -1))
    edges.sort()
    out, depth, start = [], 0, None
    for t, d in edges:
        prev = depth
        depth += d
        if prev < k <= depth:
            start = t
        elif prev >= k > depth and start is not None:
            if t > start:
                out.append((start, t))
            start = None
    return merge(out)


# ---------------------------------------------------------------------------
# span plumbing
# ---------------------------------------------------------------------------

def spans_from_chrome(doc: dict) -> list[dict]:
    """Span dicts (the snapshot() form) from a Chrome trace-event JSON
    document (`FlightRecorder.to_chrome_trace()` / `dump()` output), so
    the doctor runs offline on a dumped trace file."""
    out = []
    for ev in doc.get("traceEvents", ()):
        ph = ev.get("ph")
        if ph not in (tracing.PH_SPAN, tracing.PH_INSTANT):
            continue                        # skip metadata events
        s = {"name": ev.get("name", ""), "ph": ph,
             "ts": ev.get("ts", 0.0) / 1e6,
             "dur": ev.get("dur", 0.0) / 1e6,
             "tid": ev.get("tid", 0), "thread": "", "lane": ""}
        if "cat" in ev:
            s["cat"] = ev["cat"]
        if "args" in ev:
            s["args"] = ev["args"]
        out.append(s)
    return out


def spans_by_category(spans) -> dict[str, list[tuple[float, float]]]:
    """Merged intervals per category over a span-dict list.  Spans with
    no category (explicit or name-derived) are ignored."""
    raw: dict[str, list] = {}
    for s in spans:
        if s.get("ph") != tracing.PH_SPAN or s["dur"] <= 0:
            continue
        cat = s.get("cat") or tracing.default_category(s["name"])
        if cat is None:
            continue
        raw.setdefault(cat, []).append((s["ts"], s["ts"] + s["dur"]))
    return {c: merge(ivs) for c, ivs in raw.items()}


def find_windows(spans, key: str = "window") -> dict:
    """Group spans carrying `key` in their args; a window's interval is
    [earliest start, latest end] over its member spans.  Returns
    {window_id: (lo, hi)} sorted by lo."""
    groups: dict = {}
    for s in spans:
        args = s.get("args") or {}
        if key not in args or s.get("ph") != tracing.PH_SPAN:
            continue
        w = args[key]
        lo, hi = s["ts"], s["ts"] + s["dur"]
        if w in groups:
            groups[w] = (min(groups[w][0], lo), max(groups[w][1], hi))
        else:
            groups[w] = (lo, hi)
    return dict(sorted(groups.items(), key=lambda kv: kv[1][0]))


def attribute_interval(cat_ivs: dict, lo: float, hi: float) -> dict:
    """Priority-partition [lo, hi): each instant goes to the highest-
    priority category covering it; the uncovered remainder is idle.
    Components sum to wall exactly (float rounding aside)."""
    wall = hi - lo
    remaining = [(lo, hi)]
    out = {"wall": wall}
    for cat in PARTITION:
        cover = clip(cat_ivs.get(cat, ()), lo, hi)
        taken = intersect(remaining, cover)
        out[_REPORT_KEY[cat]] = total(taken)
        remaining = subtract(remaining, cover)
    out["device_idle"] = total(remaining)
    # pipeline stats (not part of the partition): stage unions + overlap
    prep = clip(cat_ivs.get(tracing.CAT_PREP, ()), lo, hi)
    dev = clip(cat_ivs.get(tracing.CAT_DEVICE, ()), lo, hi)
    apply_ = clip(cat_ivs.get(tracing.CAT_APPLY, ()), lo, hi)
    out["prep_seconds"] = total(prep)
    out["apply_seconds"] = total(apply_)
    out["overlap_fraction"] = (
        total(covered_by_at_least([merge(prep), merge(dev),
                                   merge(apply_)], 2)) / wall
        if wall > 0 else 0.0)
    return out


def window_attribution(spans, key: str = "window") -> list[dict]:
    """Per-window attribution table: one partition dict per window id
    found under `key` (category intervals come from ALL spans — compile
    or transfer spans need not carry the window arg to be attributed to
    the window they overlap)."""
    cat_ivs = spans_by_category(spans)
    out = []
    for w, (lo, hi) in find_windows(spans, key).items():
        row = attribute_interval(cat_ivs, lo, hi)
        row["window"] = w
        row["start"] = lo
        out.append(row)
    return out


def overlap_summary(rows: list[dict]) -> dict:
    """Collapse a `window_attribution` table into the three numbers a
    replay result carries: window count, wall-weighted mean
    overlap_fraction, and the worst window's overlap.  The weighting
    matters — a pipeline that overlaps beautifully on short windows and
    serializes on the long ones must not report a flattering mean."""
    rows = [r for r in rows if (r.get("wall") or 0.0) > 0]
    if not rows:
        return {"windows": 0, "overlap_fraction": 0.0,
                "min_window_overlap": 0.0}
    wall = sum(r["wall"] for r in rows)
    mean = sum(r["overlap_fraction"] * r["wall"] for r in rows) / wall
    return {"windows": len(rows),
            "overlap_fraction": round(mean, 4),
            "min_window_overlap": round(
                min(r["overlap_fraction"] for r in rows), 4)}


def observe_window_metrics(attr: dict) -> None:
    """Feed one window's attribution into the Prometheus histograms so
    a scrape sees the pipeline health without running the doctor."""
    from tendermint_tpu.utils.metrics import REGISTRY
    wall = attr.get("wall") or 0.0
    if wall <= 0:
        return
    REGISTRY.window_overlap_frac_hist.observe(attr["overlap_fraction"])
    REGISTRY.window_device_busy_frac_hist.observe(
        attr["device_busy"] / wall)
    REGISTRY.window_device_idle_frac_hist.observe(
        attr["device_idle"] / wall)
    REGISTRY.window_scalar_seconds.observe(attr["scalar_tail"])


# ---------------------------------------------------------------------------
# the doctor report
# ---------------------------------------------------------------------------

# components a faster pipeline would claw back (device_busy is the
# productive part; everything else is the gap)
_THIEVES = ("compile", "device_idle", "transfer", "scalar_tail")


def batchplane_summary(metrics: dict) -> dict | None:
    """Batch-plane coalescing health from a `REGISTRY.snapshot()` dict:
    how full the flushed chunks ran, who filled them, and why they
    shipped.  None when the plane never flushed (nothing to say).

    `half_full_stolen_seconds` is added by `doctor_report`: device-busy
    time estimated wasted on padding lanes, device_busy * (1 - mean
    occupancy) — the padded tail of a chunk costs the same device time
    as the real lanes, so a plane flushing half-full burns about half
    its device-busy seconds verifying zeros."""
    occ = metrics.get("batchplane_occupancy") or {}
    flushes = metrics.get("batchplane_flushes") or 0
    if not flushes or not occ.get("count"):
        return None
    return {
        "flushes": flushes,
        "mixed_batches": metrics.get("batchplane_mixed_batches", 0),
        "occupancy_mean": round(occ["sum"] / occ["count"], 4),
        "occupancy_p50": occ.get("p50"),
        "flush_reason": dict(metrics.get("batchplane_flush_reason") or {}),
        "lanes_by_producer": dict(metrics.get("batchplane_lanes") or {}),
        "wait_seconds": metrics.get("batchplane_wait_seconds") or {},
    }


def doctor_report(spans, key: str = "window",
                  regressions: dict | None = None,
                  metrics: dict | None = None) -> dict:
    """Machine-readable attribution report over a span dump.

    `headline_gap` sums the partition across all windows (falling back
    to the full span extent when no window-keyed spans exist), and
    `largest_thief` names the single biggest non-productive component —
    the first thing to fix on the road back to the 20x target.
    `regressions` (from utils/ledger.py) is folded in verbatim so one
    document answers both "where did the time go" and "did we get
    slower".  `metrics` (a `REGISTRY.snapshot()` dict) adds the batch
    plane's coalescing health and lets half-full batches compete as a
    named thief — padding lanes burn device-busy time the partition
    alone would misread as productive."""
    windows = window_attribution(spans, key)
    cat_ivs = spans_by_category(spans)
    if windows:
        gap = {k: sum(w[k] for w in windows)
               for k in ("wall", "compile", "transfer", "device_busy",
                         "scalar_tail", "device_idle")}
        overlap = (sum(w["overlap_fraction"] * w["wall"] for w in windows)
                   / gap["wall"]) if gap["wall"] > 0 else 0.0
    else:
        # no window-keyed spans: attribute the whole recorded extent
        ext = [(s["ts"], s["ts"] + s["dur"]) for s in spans
               if s.get("ph") == tracing.PH_SPAN and s["dur"] > 0]
        if ext:
            lo = min(s for s, _ in ext)
            hi = max(e for _, e in ext)
            gap = attribute_interval(cat_ivs, lo, hi)
            overlap = gap.pop("overlap_fraction")
            gap.pop("prep_seconds", None)
            gap.pop("apply_seconds", None)
        else:
            gap = {k: 0.0 for k in ("wall", "compile", "transfer",
                                    "device_busy", "scalar_tail",
                                    "device_idle")}
            overlap = 0.0
    gap = {k: round(v, 4) for k, v in gap.items()}
    thief_pool = {k: gap.get(k, 0.0) for k in _THIEVES}
    plane = batchplane_summary(metrics) if metrics else None
    if plane is not None:
        # half-full batches steal from INSIDE device_busy: the padded
        # chunk tail costs real device time, so it races the partition
        # components as its own thief rather than adding to the sum
        plane["half_full_stolen_seconds"] = round(
            gap.get("device_busy", 0.0) * (1.0 - plane["occupancy_mean"]),
            4)
        thief_pool["half_full_batches"] = plane["half_full_stolen_seconds"]
    thief = max(thief_pool, key=lambda k: thief_pool[k])
    report = {
        "schema": DOCTOR_SCHEMA,
        "span_count": len(spans),
        "window_count": len(windows),
        "headline_gap": gap,
        "overlap_fraction": round(overlap, 4),
        "largest_thief": (thief if thief_pool.get(thief, 0.0) > 0
                          else None),
        "windows": [{k: (round(v, 4) if isinstance(v, float) else v)
                     for k, v in w.items()} for w in windows],
    }
    if plane is not None:
        report["batchplane"] = plane
    if regressions is not None:
        report["regressions"] = regressions
    return report


def render_report(report: dict) -> str:
    """Human summary of a doctor report — one paragraph an operator can
    read off a terminal, naming the largest thief first."""
    gap = report["headline_gap"]
    plane = report.get("batchplane") or {}
    wall = gap.get("wall") or 0.0
    lines = []
    thief = report.get("largest_thief")
    if thief and wall > 0:
        stolen = (plane.get("half_full_stolen_seconds", 0.0)
                  if thief == "half_full_batches" else gap[thief])
        pct = 100.0 * stolen / wall
        lines.append(
            f"largest thief: {thief} ({stolen:.1f}s, {pct:.0f}% of "
            f"{wall:.1f}s window wall clock)")
    elif wall > 0:
        lines.append(f"no attributable gap found in {wall:.1f}s of "
                     "window wall clock")
    else:
        lines.append("no spans to attribute (empty flight recorder?)")
    if wall > 0:
        parts = ", ".join(
            f"{k}={gap.get(k, 0.0):.1f}s"
            for k in ("compile", "transfer", "device_busy", "scalar_tail",
                      "device_idle"))
        lines.append(f"partition: {parts}")
        lines.append(f"pipeline overlap fraction: "
                     f"{report['overlap_fraction']:.2f} over "
                     f"{report['window_count']} window(s)")
    if plane:
        reasons = ", ".join(f"{k}={v}" for k, v in
                            sorted(plane["flush_reason"].items()))
        lines.append(
            f"batch plane: {plane['flushes']} flushes "
            f"({plane['mixed_batches']} mixed-producer), occupancy "
            f"mean {plane['occupancy_mean']:.2f}, ~"
            f"{plane.get('half_full_stolen_seconds', 0.0):.1f}s burned "
            f"on padding lanes"
            + (f" [{reasons}]" if reasons else ""))
    regs = report.get("regressions") or {}
    flagged = {k: v for k, v in regs.items()
               if isinstance(v, dict) and v.get("regression")}
    for cfg, r in sorted(flagged.items()):
        lines.append(
            f"REGRESSION {cfg}: {r['rate']:.1f} {r.get('unit', '')} vs "
            f"best prior {r['best_prior']:.1f} "
            f"({100 * r['delta_frac']:+.1f}%)")
    return "\n".join(lines)
