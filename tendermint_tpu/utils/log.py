"""Structured leveled kv logging.

Reference: tmlibs/log (structured kv logger) with per-module levels parsed
from a `log_level` spec like ``state:info,p2p:debug,*:error``
(reference `config/config.go:157-159`, `cmd/tendermint/commands/root.go:43-46`).

One line per record: ``HH:MM:SS.mmm LVL  module  message key=value ...``.
Level checks are two dict lookups — cheap enough for hot paths; formatting
only happens for records that pass the filter.
"""

from __future__ import annotations

import sys
import threading
import time

DEBUG, INFO, WARN, ERROR, NONE = 10, 20, 30, 40, 100

_LEVELS = {"debug": DEBUG, "info": INFO, "warn": WARN, "error": ERROR,
           "none": NONE}
_NAMES = {DEBUG: "DBG", INFO: "INF", WARN: "WRN", ERROR: "ERR"}

_lock = threading.Lock()
_module_levels: dict[str, int] = {}
_default_level = INFO
_sink = None          # callable(str) or None -> stderr
_loggers: dict[str, "Logger"] = {}


def set_level_spec(spec: str) -> None:
    """Parse ``module:level,...`` with ``*`` as the default
    (e.g. ``consensus:debug,*:error``).  A bare level applies to all."""
    global _default_level
    with _lock:
        _module_levels.clear()
        for part in (spec or "").split(","):
            part = part.strip()
            if not part:
                continue
            if ":" in part:
                mod, _, lvl = part.partition(":")
                level = _LEVELS.get(lvl.strip().lower())
                if level is None:
                    raise ValueError(f"unknown log level {lvl!r}")
                if mod.strip() == "*":
                    _default_level = level
                else:
                    _module_levels[mod.strip()] = level
            else:
                level = _LEVELS.get(part.lower())
                if level is None:
                    raise ValueError(f"unknown log level {part!r}")
                _default_level = level


def set_sink(fn) -> None:
    """Redirect log output (tests, file sinks).  None = stderr."""
    global _sink
    _sink = fn


def _emit(line: str) -> None:
    sink = _sink
    if sink is not None:
        sink(line)
    else:
        print(line, file=sys.stderr, flush=True)


def _fmt_val(v) -> str:
    if isinstance(v, bytes):
        return v.hex()[:16]
    if isinstance(v, float):
        return f"{v:.4g}"
    s = str(v)
    if " " in s or "=" in s:
        return repr(s)
    return s


class Logger:
    __slots__ = ("module", "_bound")

    def __init__(self, module: str, bound: tuple = ()):
        self.module = module
        self._bound = bound

    def with_(self, **kv) -> "Logger":
        """A child logger with extra key=value context on every record."""
        return Logger(self.module, self._bound + tuple(kv.items()))

    def enabled(self, level: int) -> bool:
        return level >= _module_levels.get(self.module, _default_level)

    def _log(self, level: int, msg: str, kv: dict) -> None:
        if not self.enabled(level):
            return
        t = time.time()
        ms = int((t % 1) * 1000)
        stamp = time.strftime("%H:%M:%S", time.localtime(t))
        parts = [f"{stamp}.{ms:03d} {_NAMES[level]} {self.module:<10} {msg}"]
        for k, v in self._bound:
            parts.append(f"{k}={_fmt_val(v)}")
        for k, v in kv.items():
            parts.append(f"{k}={_fmt_val(v)}")
        _emit(" ".join(parts))

    def debug(self, msg: str, **kv) -> None:
        self._log(DEBUG, msg, kv)

    def info(self, msg: str, **kv) -> None:
        self._log(INFO, msg, kv)

    def warn(self, msg: str, **kv) -> None:
        self._log(WARN, msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._log(ERROR, msg, kv)

    def exception(self, msg: str, **kv) -> None:
        """error + traceback of the active exception — the replacement for
        bare traceback.print_exc in must-not-die loops."""
        import traceback
        self._log(ERROR, msg, kv)
        if self.enabled(ERROR):
            _emit(traceback.format_exc().rstrip())


def get_logger(module: str) -> Logger:
    with _lock:
        lg = _loggers.get(module)
        if lg is None:
            lg = _loggers[module] = Logger(module)
        return lg
