"""Bench regression ledger: one JSONL line per bench run.

The scoreboard problem: round 4 hit 28.77x, round 5 timed out at ~12x,
and nothing in the repo recorded the trajectory in between.  The ledger
fixes that — `bench.py` appends an entry per run (per-config rates plus
the doctor's attribution partition), `cli bench-history` renders the
trajectory, and `compute_deltas` compares each config against the BEST
prior run so a slow creep over five runs is as visible as a cliff in
one.

Entries are append-only JSONL (one object per line) so concurrent
readers never see a torn file and a crashed bench leaves prior history
intact.  `load()` tolerates corrupt/partial lines: a run killed mid-
append must not brick the history command.
"""

from __future__ import annotations

import json
import os

LEDGER_SCHEMA = "tpu-bft-bench-ledger/1"

DEFAULT_PATH = "BENCH_LEDGER.jsonl"

# a config "regresses" when its rate drops more than this fraction below
# the best prior run's rate for the same config
DEFAULT_REGRESSION_THRESHOLD = 0.15

# headline rate key per bench config (bench.py result dicts)
RATE_KEYS = {
    "config0": "blocks_per_sec",
    "config1": "sigs_per_sec",
    "config2": "trees_per_sec",
    "config3": "sigs_per_sec",
    "config4": "sigs_per_sec",
}


def rate_of(config_name: str, result: dict):
    """(rate, unit) for a config result, or (None, None) when the result
    has no recognizable headline rate (e.g. an errored config)."""
    key = RATE_KEYS.get(config_name)
    if key and isinstance(result.get(key), (int, float)):
        return float(result[key]), key
    # fall back to any *_per_sec field so unknown configs still track
    for k, v in sorted(result.items()):
        if k.endswith("_per_sec") and isinstance(v, (int, float)):
            return float(v), k
    return None, None


def load(path: str) -> list[dict]:
    """All parseable entries oldest-first; corrupt or truncated lines
    are skipped (a run killed mid-append must not brick history)."""
    entries: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except ValueError:
                    continue
                if isinstance(e, dict):
                    entries.append(e)
    except OSError:
        return []
    return entries


def append_entry(path: str, entry: dict) -> None:
    """Append one entry as a single JSONL line (O_APPEND + fsync: the
    line is either fully present or absent, never interleaved)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    line = json.dumps(entry, sort_keys=True) + "\n"
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        os.write(fd, line.encode())
        os.fsync(fd)
    finally:
        os.close(fd)


def best_prior(entries: list[dict]) -> dict:
    """{config_name: (best_rate, unit)} over prior entries."""
    best: dict = {}
    for e in entries:
        for cfg, res in (e.get("configs") or {}).items():
            if not isinstance(res, dict):
                continue
            rate, unit = rate_of(cfg, res)
            if rate is None:
                continue
            if cfg not in best or rate > best[cfg][0]:
                best[cfg] = (rate, unit)
    return best


def compute_deltas(prior_entries: list[dict], configs: dict,
                   threshold: float = DEFAULT_REGRESSION_THRESHOLD) -> dict:
    """Per-config comparison of `configs` (this run's results) against
    the best prior rate.  Returns {config: {rate, unit, best_prior,
    delta_frac, regression}}; configs with no prior history get
    best_prior=None and regression=False (a first run cannot regress)."""
    best = best_prior(prior_entries)
    out: dict = {}
    for cfg, res in configs.items():
        if not isinstance(res, dict):
            continue
        rate, unit = rate_of(cfg, res)
        if rate is None:
            continue
        row = {"rate": rate, "unit": unit, "best_prior": None,
               "delta_frac": None, "regression": False}
        if cfg in best and best[cfg][0] > 0:
            prior = best[cfg][0]
            row["best_prior"] = prior
            row["delta_frac"] = (rate - prior) / prior
            row["regression"] = row["delta_frac"] < -threshold
        out[cfg] = row
    return out


def render_history(entries: list[dict]) -> str:
    """Trajectory table for `cli bench-history`: one block per run with
    each config's rate and its delta vs the best of all PRIOR runs."""
    if not entries:
        return "ledger is empty (run bench.py to append an entry)"
    lines = []
    for i, e in enumerate(entries):
        when = e.get("timestamp") or e.get("git") or f"run {i + 1}"
        mode = "quick" if e.get("quick") else "full"
        lines.append(f"[{i + 1}] {when} ({mode})")
        deltas = compute_deltas(entries[:i], e.get("configs") or {})
        for cfg in sorted(deltas):
            r = deltas[cfg]
            note = ""
            if r["best_prior"] is not None:
                note = f"  ({100 * r['delta_frac']:+.1f}% vs best prior"
                note += ", REGRESSION)" if r["regression"] else ")"
            lines.append(f"    {cfg}: {r['rate']:.2f} {r['unit']}{note}")
        if not deltas:
            lines.append("    (no rates recorded)")
    return "\n".join(lines)
