"""Deterministic device-fault injection for the crypto hot path.

Extends the `utils/fail.py` env-var pattern (crash points selected by
TM_FAIL_INDEX / TM_FAIL_POINT) to RUNTIME device faults: TM_CHAOS_CRYPTO
selects a failure mode the supervised crypto backend injects into its
device rung, so fallback/breaker behavior is testable without real
hardware failures.

Spec grammar (one mode, comma-separated k=v params):

    TM_CHAOS_CRYPTO=raise:every=N        raise a DeviceFault on every Nth
                                         device call
    TM_CHAOS_CRYPTO=latency:ms=X,every=N sleep X ms before every Nth call
                                         (exercises the per-call timeout)
    TM_CHAOS_CRYPTO=wrong:lanes=K,every=N  flip the first K result lanes
                                         of every Nth call (exercises the
                                         spot-check re-verification)

`every` defaults to 1 (every call).  The schedule is a pure function of
the call counter, so a given spec produces the identical fault sequence
on every run — lossy-device regressions replay exactly, the same promise
`FuzzedConnection(seed=...)` makes for lossy networks.

This module is also the single home of chaos CONFIGURATION: the
scenario engine (`tendermint_tpu/scenarios/`) installs a validated
`ChaosConfig` programmatically via `install()`, and every consumer that
used to read raw env strings (`SupervisedBackend` -> TM_CHAOS_CRYPTO,
`FuzzedConnection` seeding) asks this module first.  Env vars remain
the standalone-node path; an installed config always wins, so a
scenario never depends on process-global environment mutation.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time


class DeviceFault(RuntimeError):
    """An infrastructure failure in a crypto backend: XLA/runtime error,
    OOM, timeout, hang, or a wrong-answer spot-check mismatch.  NEVER a
    statement about signature validity — callers must retry/fall back,
    not report "bad signature" or punish peers."""


class CryptoChaos:
    """One parsed TM_CHAOS_CRYPTO policy with a deterministic call
    counter.  `before_call` runs the raise/latency modes; `corrupt`
    applies the wrong-answer mode to a bool result array."""

    MODES = ("raise", "latency", "wrong")

    def __init__(self, mode: str, every: int = 1, ms: float = 0.0,
                 lanes: int = 1):
        if mode not in self.MODES:
            raise ValueError(f"unknown chaos mode {mode!r}; "
                             f"known: {self.MODES}")
        if every < 1:
            raise ValueError("chaos every= must be >= 1")
        self.mode = mode
        self.every = every
        self.ms = ms
        self.lanes = lanes
        self.active = True          # tests flip this to "clear" injection
        self._count = 0
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "CryptoChaos":
        """Parse ``mode:key=val,key=val``.  Raises ValueError on junk —
        a typo'd chaos spec silently injecting nothing would make a
        passing chaos test meaningless."""
        mode, _, rest = spec.partition(":")
        kw: dict = {}
        for part in filter(None, (p.strip() for p in rest.split(","))):
            k, eq, v = part.partition("=")
            if not eq:
                raise ValueError(f"chaos param {part!r} is not k=v")
            if k == "every":
                kw["every"] = int(v)
            elif k == "ms":
                kw["ms"] = float(v)
            elif k == "lanes":
                kw["lanes"] = int(v)
            else:
                raise ValueError(f"unknown chaos param {k!r} in {spec!r}")
        return cls(mode.strip(), **kw)

    @classmethod
    def from_env(cls) -> "CryptoChaos | None":
        spec = os.environ.get("TM_CHAOS_CRYPTO", "")
        return cls.parse(spec) if spec else None

    @classmethod
    def current(cls) -> "CryptoChaos | None":
        """The crypto-chaos policy in effect: the installed ChaosConfig's
        (scenario engine, programmatic) when one is present, else the
        TM_CHAOS_CRYPTO env spec (standalone node)."""
        cfg = installed()
        if cfg is not None:
            return cfg.crypto
        return cls.from_env()

    def _fire(self) -> bool:
        """Advance the counter; True when this call is selected."""
        if not self.active:
            return False
        with self._lock:
            self._count += 1
            return self._count % self.every == 0

    @property
    def calls(self) -> int:
        return self._count

    def before_call(self) -> None:
        """Raise/latency injection, run where a real device error would
        surface (inside the supervised device-rung invocation)."""
        if self.mode == "wrong":
            return                   # handled after the call, in corrupt()
        if not self._fire():
            return
        if self.mode == "raise":
            raise DeviceFault(
                f"chaos: injected device fault (call {self._count})")
        time.sleep(self.ms / 1000.0)

    def corrupt(self, out):
        """Wrong-answer mode: flip the first `lanes` lanes of a bool
        result — the failure shape of a silently corrupting device, which
        only a reference spot check can catch."""
        if self.mode != "wrong" or not self._fire():
            return out
        import numpy as np
        out = np.array(out, dtype=bool, copy=True)
        k = min(self.lanes, len(out))
        out[:k] = ~out[:k]
        return out


# ---------------------------------------------------------------------------
# seed derivation + the installed chaos configuration
# ---------------------------------------------------------------------------

def derive_seed(seed: int, *labels: str) -> int:
    """A child seed for the injector named by `labels`, as a pure
    function of the master seed: sha256 over "seed/label/label/...".
    Independent injectors get decorrelated streams, and the whole tree
    replays from the one integer the scenario was launched with."""
    key = "/".join((str(int(seed)),) + tuple(labels))
    return int.from_bytes(hashlib.sha256(key.encode()).digest()[:8], "big")


class ChaosConfig:
    """The single validated chaos-configuration object.

    One instance describes everything a fault scenario injects below
    the scenario engine's own line of sight:

      seed    master integer seed; every injector RNG (FuzzedConnection,
              byzantine vote schedules, crash schedules) derives from it
              via `derive_seed`, so one integer replays the whole run
      crypto  device-fault policy for the supervised crypto ladder —
              a CryptoChaos, a spec string ("raise:every=50", validated
              here, at construction), or None for no injection

    Install with `install(cfg)`; consumers read `installed()` (or the
    `CryptoChaos.current()` convenience).  The env-var path
    (TM_CHAOS_CRYPTO / TM_CHAOS_SEED via `from_env`) builds the same
    object, so there is exactly one parse/validation site either way.
    """

    def __init__(self, seed: int = 0,
                 crypto: "CryptoChaos | str | None" = None):
        if isinstance(seed, bool) or not isinstance(seed, int):
            raise ValueError(f"chaos seed must be an int, got {seed!r}")
        if isinstance(crypto, str):
            crypto = CryptoChaos.parse(crypto) if crypto else None
        if crypto is not None and not isinstance(crypto, CryptoChaos):
            raise ValueError("chaos crypto= must be a CryptoChaos, a "
                             f"spec string, or None; got {crypto!r}")
        self.seed = seed
        self.crypto = crypto

    @classmethod
    def from_env(cls) -> "ChaosConfig":
        return cls(seed=int(os.environ.get("TM_CHAOS_SEED", "0") or 0),
                   crypto=CryptoChaos.from_env())

    def derive_seed(self, *labels: str) -> int:
        return derive_seed(self.seed, *labels)


_installed: "ChaosConfig | None" = None
_installed_lock = threading.Lock()


def install(cfg: "ChaosConfig | None") -> "ChaosConfig | None":
    """Set (or with None, clear) the process-wide chaos config; returns
    the previous one so scenario runners can restore it in a finally."""
    global _installed
    if cfg is not None and not isinstance(cfg, ChaosConfig):
        raise ValueError(f"install() takes a ChaosConfig, got {cfg!r}")
    with _installed_lock:
        prev, _installed = _installed, cfg
    return prev


def installed() -> "ChaosConfig | None":
    with _installed_lock:
        return _installed
