"""Byte-rate metering for connections and fast-sync peers.

Reference: tmlibs/flowrate `Monitor` — the reference samples transfer
progress into an EMA and exposes `Status{AvgRate, Bytes, ...}`; fast-sync
evicts peers whose receive rate falls under 10 KB/s
(`blockchain/pool.go:14-19,100-118`) and `net_info` exposes per-connection
send/recv snapshots (`p2p/connection.go:485-515`).  This is a compact
equivalent: fixed sampling windows folded into an exponential moving
average, lock-free enough for per-packet updates.
"""

from __future__ import annotations

import threading
import time

_WINDOW = 0.25      # seconds per sample window
_ALPHA = 0.1        # EMA weight of the newest window — slow enough that
                    # a healthy peer mid-transfer (bytes land only on
                    # block completion) does not decay under an eviction
                    # threshold within a couple of empty windows


class Meter:
    """Exponentially-averaged byte rate plus totals."""

    def __init__(self, now: float | None = None):
        self._lock = threading.Lock()
        self._start = now if now is not None else time.monotonic()
        self._window_start = self._start
        self._window_bytes = 0
        self._rate = 0.0
        self.total = 0

    def update(self, nbytes: int, now: float | None = None) -> None:
        now = now if now is not None else time.monotonic()
        with self._lock:
            self.total += nbytes
            self._roll(now)
            self._window_bytes += nbytes

    def _roll(self, now: float) -> None:
        elapsed = now - self._window_start
        if elapsed < _WINDOW:
            return
        n = int(elapsed / _WINDOW)
        sample = self._window_bytes / _WINDOW
        self._rate = (_ALPHA * sample + (1 - _ALPHA) * self._rate
                      if self._rate or sample else 0.0)
        if n > 1:
            # the remaining n-1 windows are empty: decay in closed form
            # instead of iterating (an hour-idle meter would otherwise
            # spin ~14k loop iterations under the lock)
            self._rate *= (1 - _ALPHA) ** (n - 1)
        self._window_bytes = 0
        self._window_start += n * _WINDOW

    def rate(self, now: float | None = None) -> float:
        """Bytes/second, exponentially averaged over recent windows."""
        now = now if now is not None else time.monotonic()
        with self._lock:
            self._roll(now)
            return self._rate

    def age(self, now: float | None = None) -> float:
        now = now if now is not None else time.monotonic()
        return now - self._start

    def status(self) -> dict:
        return {"rate_bytes_per_sec": round(self.rate(), 1),
                "total_bytes": self.total,
                "age_seconds": round(self.age(), 2)}
