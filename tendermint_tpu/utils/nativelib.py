"""ctypes binding for the native host runtime (native/tmhash.cpp).

Builds the shared library on demand with g++ (the environment's native
toolchain; no pybind11) into the repo's native/ dir, caching the .so next
to its source.  Every entry point degrades to None when the toolchain or
library is unavailable — callers fall back to hashlib paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from tendermint_tpu.utils.log import get_logger

log = get_logger("nativelib")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "native")
_SRC = os.path.join(_NATIVE_DIR, "tmhash.cpp")
_SO = os.path.join(_NATIVE_DIR, "libtmhash.so")


def _build() -> bool:
    try:
        r = subprocess.run(
            ["g++", "-O2", "-std=c++17", "-fPIC", "-pthread", "-shared",
             "-o", _SO, _SRC],
            capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            log.warn("native build failed", err=r.stderr[-500:])
            return False
        return True
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warn("native build unavailable", err=str(e))
        return False


def get() -> ctypes.CDLL | None:
    """The loaded library, building it if needed; None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC):
            return None
        if (not os.path.exists(_SO) or
                os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as e:
            log.warn("native lib load failed", err=str(e))
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.tm_leaf_hashes.argtypes = [u8p, ctypes.c_uint64,
                                       ctypes.c_uint64, u8p,
                                       ctypes.c_uint32]
        lib.tm_merkle_roots.argtypes = [u8p, ctypes.c_uint64,
                                        ctypes.c_uint64, ctypes.c_uint64,
                                        u8p, ctypes.c_uint32]
        _lib = lib
        return _lib


def _threads() -> int:
    return min(16, os.cpu_count() or 1)


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def leaf_hashes(msgs: np.ndarray) -> np.ndarray | None:
    """uint8[N, L] -> 0x00-prefixed sha256 digests uint8[N, 32]."""
    lib = get()
    if lib is None:
        return None
    msgs = np.ascontiguousarray(msgs, dtype=np.uint8)
    n, ln = msgs.shape
    out = np.empty((n, 32), dtype=np.uint8)
    lib.tm_leaf_hashes(_u8p(msgs), n, ln, _u8p(out), _threads())
    return out


def merkle_roots(leaves: np.ndarray) -> np.ndarray | None:
    """uint8[T, N, L] equal-shape trees -> roots uint8[T, 32]
    (reference-shaped (n+1)//2 split, domain-separated)."""
    lib = get()
    if lib is None:
        return None
    leaves = np.ascontiguousarray(leaves, dtype=np.uint8)
    t, n, ln = leaves.shape
    out = np.empty((t, 32), dtype=np.uint8)
    lib.tm_merkle_roots(_u8p(leaves), t, n, ln, _u8p(out), _threads())
    return out
