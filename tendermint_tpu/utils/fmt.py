"""Tiny shared formatters for debug/RPC dumps."""

from __future__ import annotations


def bits_str(b) -> str | None:
    """Bool list -> compact bit-array string ('x_x_'), None passthrough —
    the reference BitArray rendering used by dump_consensus_state."""
    if b is None:
        return None
    return "".join("x" if v else "_" for v in b)
