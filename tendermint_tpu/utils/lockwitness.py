"""Runtime lock-order witness: catch lock inversions when they happen.

The static checker (`analysis/locks.py`) sees the lock-acquisition
graph the source admits; this module sees the one the running process
actually walks.  With ``TM_LOCK_WITNESS=1`` in the environment,
``new_lock(name)`` returns a :class:`WitnessLock` that records, per
thread, the stack of witness locks currently held, and folds every
(held -> acquiring) pair into a process-global order graph.  The first
acquisition that contradicts an edge already in the graph — lock B
taken under A somewhere, A now being taken under B — raises
:class:`LockOrderError` at the acquisition site, naming both orders.
That converts a once-a-week deadlock hang into a deterministic
traceback in whichever test first exercises both orders, without
needing the two threads to actually race.

Without the env var, ``new_lock`` returns a plain
``threading.Lock``/``RLock`` — zero overhead in production.

Modeled on Go's lock-order witness in btcd/go-ethereum test builds and
the FreeBSD ``WITNESS(4)`` kernel option.
"""

from __future__ import annotations

import os
import threading

_ENV = "TM_LOCK_WITNESS"


class LockOrderError(RuntimeError):
    """Two witness locks were taken in contradicting orders."""


# process-global order graph: edge (a, b) means "b was acquired while a
# was held", tagged with the thread name that first recorded it.  The
# graph only ever grows; reset() exists for tests.
_graph_mtx = threading.Lock()
_edges: dict[tuple[str, str], str] = {}
_tls = threading.local()


def enabled() -> bool:
    return os.environ.get(_ENV, "") == "1"


def reset() -> None:
    """Drop all recorded edges (test isolation)."""
    with _graph_mtx:
        _edges.clear()


def edges() -> dict[tuple[str, str], str]:
    """Snapshot of the recorded order graph (for tests/diagnostics)."""
    with _graph_mtx:
        return dict(_edges)


def _held_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class WitnessLock:
    """A named lock that participates in the global order graph.

    Mirrors the threading lock surface the codebase uses: acquire /
    release / context manager / locked().  Reentrant re-acquisition of
    the same witness lock records no edge (an RLock held twice is one
    node, not a cycle).
    """

    def __init__(self, name: str, reentrant: bool = True):
        self.name = name
        self._inner = (threading.RLock() if reentrant
                       else threading.Lock())

    def _check_order(self) -> None:
        stack = _held_stack()
        if not stack:
            return
        tname = threading.current_thread().name
        with _graph_mtx:
            for held in stack:
                if held.name == self.name:
                    continue            # reentrant: same node
                fwd = (held.name, self.name)
                rev = (self.name, held.name)
                if rev in _edges:
                    raise LockOrderError(
                        f"lock order inversion: acquiring "
                        f"'{self.name}' while holding '{held.name}' "
                        f"(thread {tname!r}), but thread "
                        f"{_edges[rev]!r} previously acquired "
                        f"'{held.name}' while holding '{self.name}'")
                _edges.setdefault(fwd, tname)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._check_order()
        got = self._inner.acquire(blocking, timeout)
        if got:
            _held_stack().append(self)
        return got

    def release(self) -> None:
        self._inner.release()
        stack = _held_stack()
        # remove the most recent entry for this lock (locks are almost
        # always released LIFO, but .acquire()/.release() pairs in the
        # codebase occasionally interleave)
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                break

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        # Lock has .locked(); RLock doesn't expose one portably
        inner_locked = getattr(self._inner, "locked", None)
        return inner_locked() if inner_locked else any(
            l is self for l in _held_stack())

    def __repr__(self):
        return f"WitnessLock({self.name!r})"


def new_lock(name: str, reentrant: bool = True):
    """A lock for `name`: a WitnessLock under TM_LOCK_WITNESS=1, else a
    plain threading lock.  `name` should be stable across instances of
    the same class ('consensus.mtx', 'mempool.lock') so the order graph
    aggregates by ROLE — an inversion between any consensus lock and
    any mempool lock is the bug, whichever instances exhibit it."""
    if enabled():
        return WitnessLock(name, reentrant=reentrant)
    return threading.RLock() if reentrant else threading.Lock()
