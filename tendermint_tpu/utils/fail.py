"""Crash-point injection for persistence tests.

Reference: ebuchman/fail-test (`glide.yaml:5`) — `fail.Fail()` call sites
abort the process when FAIL_TEST_INDEX selects them
(`consensus/state.go:1285-1346`, `state/execution.go:218-237`;
exercised by `test/persist/test_failure_indices.sh`).

Here fail points are *named* and counted: TM_FAIL_INDEX=i kills the
process at the i-th hit; TM_FAIL_POINT=name kills at a named site.
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_counter = 0
_callback = None


def set_callback(cb) -> None:
    """Testing hook: called instead of os._exit (in-process crash sim)."""
    global _callback
    _callback = cb


def fail_point(name: str) -> None:
    global _counter
    target_idx = os.environ.get("TM_FAIL_INDEX")
    target_name = os.environ.get("TM_FAIL_POINT")
    if target_idx is None and target_name is None:
        return
    with _lock:
        idx = _counter
        _counter += 1
    hit = ((target_idx is not None and idx == int(target_idx)) or
           (target_name is not None and name == target_name))
    if hit:
        if _callback is not None:
            _callback(name, idx)
            return
        import sys
        print(f"FAIL_POINT hit: {name} (index {idx})", file=sys.stderr,
              flush=True)
        os._exit(66)
