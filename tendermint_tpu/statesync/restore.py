"""Snapshot offer/fetch/verify: the recovering node's side.

The trust chain, in order:

1. **Offer selection** — every source advertises its manifests; offers
   group by `(height, format, root)` and the best group is the highest
   height (more providers breaks ties).  A manifest is only an OFFER —
   nothing in it is trusted yet.
2. **Light-client cross-check** — the caller supplies `verify_offer`,
   typically `verify_manifest_app_hash` over a light-client-verified
   header at `height+1` (whose `app_hash` field commits to the app
   state AFTER block `height` — exactly what the snapshot restores).
   An offer that fails the cross-check is a PROVEN lie: every provider
   is reported with `ban=True` and the next-best offer is tried.
3. **Chunk verification** — chunks fetched from the group's providers
   in parallel, then every hash verified in one batched call before a
   single byte reaches the app.  A bad chunk blames its serving peer
   (misbehavior score / ban via `p2p/switch.py`) and is refetched from
   another provider; a group that cannot complete falls through to the
   next offer, and a syncer that exhausts all offers raises
   `RestoreError` — the caller's cue to fall back to full fast-sync.
4. **Decode + apply** — payload re-roots, `State` decodes, heights and
   app hashes must agree, the app restores and (when it reports one)
   its recomputed app hash must equal the manifest's.

After `restore()` the caller replays only `snapshot_height -> tip`
through the existing windowed fast-sync (the block store is
bootstrapped at the snapshot height so the reactor's request window
starts there, not at genesis).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

from tendermint_tpu.state.state import State
from tendermint_tpu.statesync.snapshot import (SnapshotManifest,
                                               SnapshotStore,
                                               decode_payload,
                                               verify_chunk_hashes)
from tendermint_tpu.types import merkle as hmerkle
from tendermint_tpu.utils.metrics import REGISTRY
from tendermint_tpu.utils import log as log_mod

log = log_mod.get_logger("statesync")

DEFAULT_FETCHERS = 4


class RestoreError(Exception):
    """No offer could be restored; the caller falls back to full
    fast-sync from genesis."""


class StoreSource:
    """Rig-level chunk source: a peer's SnapshotStore behind a peer id.
    The TCP equivalent speaks `statesync/messages.py` over channel 0x60;
    both shapes expose the same two methods, which is all the syncer
    needs."""

    def __init__(self, peer_id: str, store: SnapshotStore):
        self.peer_id = peer_id
        self.store = store

    def manifests(self) -> list[SnapshotManifest]:
        return self.store.list()

    def chunk(self, height: int, index: int) -> bytes | None:
        return self.store.load_chunk(height, index)


def verify_manifest_app_hash(manifest: SnapshotManifest, header) -> bool:
    """The light-client cross-check: `header` is a VERIFIED header at
    `manifest.height + 1`; its app_hash commits to the app state after
    block `manifest.height` — the state this snapshot claims to hold."""
    return (header.height == manifest.height + 1
            and header.app_hash == manifest.app_hash)


class StateSyncer:
    def __init__(self, sources: list, *, report_misbehavior=None,
                 verify_offer=None, fetchers: int = DEFAULT_FETCHERS):
        """`sources`: ChunkSource-shaped objects (peer_id, manifests(),
        chunk()).  `report_misbehavior(peer_id, reason, *, ban=...)`
        feeds the p2p switch's scoring (pass the bound method of a live
        Switch, or a recorder in tests).  `verify_offer(manifest) ->
        bool` is the light-client cross-check hook; offers failing it
        are discarded WITH blame."""
        if not sources:
            raise ValueError("StateSyncer needs at least one source")
        self.sources = list(sources)
        self.report = report_misbehavior
        self.verify_offer = verify_offer
        self.fetchers = max(1, fetchers)
        self.blamed: list[tuple[str, str]] = []   # (peer_id, reason)

    # -- offers ---------------------------------------------------------
    def offers(self) -> list[tuple[SnapshotManifest, list]]:
        """Offer groups best-first: [(manifest, [sources])] sorted by
        height desc, provider count desc.  A source whose manifests()
        raises is skipped — unreachable is not malicious."""
        groups: dict[tuple, tuple[SnapshotManifest, list]] = {}
        for src in self.sources:
            try:
                ms = src.manifests()
            except Exception:
                log.exception("snapshot source unreachable",
                              peer=src.peer_id)
                continue
            for m in ms:
                key = m.key()
                if key not in groups:
                    groups[key] = (m, [])
                groups[key][1].append(src)
        return sorted(groups.values(),
                      key=lambda g: (g[0].height, len(g[1])),
                      reverse=True)

    def _blame(self, peer_id: str, reason: str, ban: bool) -> None:
        self.blamed.append((peer_id, reason))
        log.warn("statesync blame", peer=peer_id, reason=reason, ban=ban)
        if self.report is not None:
            self.report(peer_id, reason, ban=ban)

    # -- chunk fetch + verify -------------------------------------------
    def _fetch_verified(self, manifest: SnapshotManifest,
                        providers: list) -> list[bytes] | None:
        """All chunks of `manifest`, every hash verified.  Providers
        serve interleaved in parallel; a bad or missing chunk rotates to
        the next provider (bad → blame + ban).  None when the group is
        exhausted with chunks still unverified."""
        n = manifest.chunks
        chunks: dict[int, bytes] = {}
        served: dict[int, object] = {}
        banned: set[str] = set()
        lock = threading.Lock()
        order = list(providers)

        def fetch(idx: int, src) -> None:
            try:
                c = src.chunk(manifest.height, idx)
            except Exception:
                c = None
            if c is not None:
                with lock:
                    chunks[idx] = c
                    served[idx] = src

        attempts = 0
        pending = list(range(n))
        while pending and attempts < len(order) + 1:
            live = [s for s in order if s.peer_id not in banned]
            if not live:
                return None
            with ThreadPoolExecutor(
                    min(self.fetchers, len(pending))) as pool:
                futs = [pool.submit(fetch, idx, live[k % len(live)])
                        for k, idx in enumerate(pending)]
                for f in futs:
                    f.result()
            fetched = {i: chunks[i] for i in pending if i in chunks}
            bad = set(verify_chunk_hashes(fetched, manifest.chunk_hashes))
            for idx in sorted(bad):
                src = served.pop(idx)
                chunks.pop(idx, None)
                self._blame(
                    src.peer_id,
                    f"statesync: bad chunk {idx} of snapshot "
                    f"h={manifest.height} (hash mismatch)", ban=True)
                banned.add(src.peer_id)
            still_missing = [i for i in pending if i not in chunks]
            if not still_missing:
                break
            # rotate so a refetch lands on a different provider
            order = order[1:] + order[:1]
            pending = still_missing
            attempts += 1
        if len(chunks) != n:
            return None
        return [chunks[i] for i in range(n)]

    # -- restore --------------------------------------------------------
    def restore(self, db, genesis_doc, app) -> tuple[State,
                                                     SnapshotManifest]:
        """Walk offers best-first until one restores; returns the saved
        State (bound to `db`) and the manifest it came from.  Raises
        RestoreError when every offer fails."""
        t0 = time.time()
        tried = 0
        for manifest, providers in self.offers():
            tried += 1
            if self.verify_offer is not None and \
                    not self.verify_offer(manifest):
                for src in providers:
                    self._blame(
                        src.peer_id,
                        f"statesync: manifest h={manifest.height} "
                        f"app_hash fails the light-client cross-check "
                        f"(stale or forged snapshot)", ban=True)
                continue
            chunks = self._fetch_verified(manifest, providers)
            if chunks is None:
                log.warn("snapshot offer exhausted",
                         height=manifest.height,
                         providers=[s.peer_id for s in providers])
                continue
            try:
                state = self._apply(manifest, chunks, db, genesis_doc,
                                    app)
            except ValueError as e:
                # verified chunks that still decode wrong mean the
                # MANIFEST lied coherently; every provider is in on it
                for src in providers:
                    self._blame(src.peer_id,
                                f"statesync: snapshot h="
                                f"{manifest.height} failed apply: {e}",
                                ban=True)
                continue
            dt = time.time() - t0
            REGISTRY.snapshot_restore_seconds.observe(dt)
            log.info("snapshot restored", height=manifest.height,
                     chunks=manifest.chunks, seconds=round(dt, 3))
            return state, manifest
        raise RestoreError(
            f"no snapshot offer could be restored ({tried} tried); "
            f"fall back to full fast-sync")

    @staticmethod
    def _apply(manifest: SnapshotManifest, chunks: list[bytes], db,
               genesis_doc, app) -> State:
        """Decode + cross-check + hand the app its state.  Every check
        here is against material already hash-verified, so a failure
        indicts the manifest, not the transport."""
        payload = b"".join(chunks)
        # belt-and-braces: re-root the payload we are about to trust
        hashes = [hmerkle.leaf_hash(c) for c in chunks]
        if hmerkle.root_from_leaf_hashes(hashes) != manifest.root:
            raise ValueError("assembled payload does not re-root")
        state_bytes, app_state = decode_payload(payload)
        state = State.decode_bytes(state_bytes, db=db,
                                   genesis_doc=genesis_doc)
        if state.chain_id != genesis_doc.chain_id:
            raise ValueError(
                f"snapshot chain_id {state.chain_id!r} != genesis "
                f"{genesis_doc.chain_id!r}")
        if state.last_block_height != manifest.height:
            raise ValueError(
                f"snapshot state height {state.last_block_height} != "
                f"manifest height {manifest.height}")
        if state.app_hash != manifest.app_hash:
            raise ValueError("snapshot state app_hash != manifest "
                             "app_hash")
        app.restore_state(app_state)
        info = app.info()
        got = getattr(info, "last_block_app_hash", b"") or b""
        if got and got != manifest.app_hash:
            raise ValueError(
                f"restored app recomputes app_hash {got.hex()[:16]} != "
                f"manifest {manifest.app_hash.hex()[:16]}")
        state.save()
        return state
