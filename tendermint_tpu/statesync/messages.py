"""State-sync wire messages (channel 0x60).

The rig-level restore path talks to `SnapshotStore`s directly through
`StoreSource`; these messages are the same protocol spelled for the p2p
layer — a recovering node broadcasts SnapshotsRequest, providers answer
with their manifests, and chunks stream back one ChunkRequest at a time
(NoChunkResponse for pruned/unknown chunks, mirroring fast-sync's
NoBlockResponse so a syncer can rotate providers instead of hanging).
Codec-complete now so the reactor, when it lands, inherits a tested
vocabulary.
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.statesync.snapshot import SnapshotManifest
from tendermint_tpu.types.codec import Reader, lp_bytes, u32, u64, u8

STATESYNC_CHANNEL = 0x60

TAG_SNAPSHOTS_REQUEST = 0x01
TAG_SNAPSHOTS_RESPONSE = 0x02
TAG_CHUNK_REQUEST = 0x03
TAG_CHUNK_RESPONSE = 0x04
TAG_NO_CHUNK_RESPONSE = 0x05


@dataclass(frozen=True)
class SnapshotsRequest:
    pass


@dataclass(frozen=True)
class SnapshotsResponse:
    manifests: tuple[SnapshotManifest, ...]


@dataclass(frozen=True)
class ChunkRequest:
    height: int
    index: int


@dataclass(frozen=True)
class ChunkResponse:
    height: int
    index: int
    chunk: bytes


@dataclass(frozen=True)
class NoChunkResponse:
    height: int
    index: int


def encode_msg(msg) -> bytes:
    if isinstance(msg, SnapshotsRequest):
        return u8(TAG_SNAPSHOTS_REQUEST)
    if isinstance(msg, SnapshotsResponse):
        # manifests ride as their JSON encoding: the CRC frame travels
        # with them, so a receiver rejects a corrupt manifest the same
        # way it rejects a torn one on disk
        return (u8(TAG_SNAPSHOTS_RESPONSE) + u32(len(msg.manifests)) +
                b"".join(lp_bytes(m.encode_json())
                         for m in msg.manifests))
    if isinstance(msg, ChunkRequest):
        return u8(TAG_CHUNK_REQUEST) + u64(msg.height) + u32(msg.index)
    if isinstance(msg, ChunkResponse):
        return (u8(TAG_CHUNK_RESPONSE) + u64(msg.height) +
                u32(msg.index) + lp_bytes(msg.chunk))
    if isinstance(msg, NoChunkResponse):
        return (u8(TAG_NO_CHUNK_RESPONSE) + u64(msg.height) +
                u32(msg.index))
    raise TypeError(f"cannot encode {type(msg).__name__}")


def decode_msg(data: bytes):
    r = Reader(data)
    tag = r.u8()
    if tag == TAG_SNAPSHOTS_REQUEST:
        return SnapshotsRequest()
    if tag == TAG_SNAPSHOTS_RESPONSE:
        n = r.u32()
        return SnapshotsResponse(tuple(
            SnapshotManifest.decode_json(r.lp_bytes()) for _ in range(n)))
    if tag == TAG_CHUNK_REQUEST:
        return ChunkRequest(r.u64(), r.u32())
    if tag == TAG_CHUNK_RESPONSE:
        return ChunkResponse(r.u64(), r.u32(), r.lp_bytes())
    if tag == TAG_NO_CHUNK_RESPONSE:
        return NoChunkResponse(r.u64(), r.u32())
    raise ValueError(f"unknown statesync message tag {tag:#x}")
