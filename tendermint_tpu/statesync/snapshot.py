"""Snapshot creation + on-disk format.

Layout under a snapshot root directory:

    snapshots/
      snapshot-0000000500/
        chunk-000000.bin
        chunk-000001.bin
        ...
        manifest.json        # written LAST, via tmp + atomic rename

The chunked payload is `lp(state_bytes) || lp(app_state_bytes)` split
into fixed-size chunks; the manifest commits to every chunk hash
(0x00-domain-separated SHA-256 leaf hashes, same tree as every other
Merkle structure here) and their root.  Failure semantics mirror the
consensus WAL's CRC framing philosophy:

- the manifest is written last and carries a crc32 of its canonical
  body, so a crash at ANY point of snapshot creation leaves either a
  chunk directory with no (or a torn) manifest — discarded on scan —
  or a complete, verifiable snapshot;
- a manifest whose listed chunk hashes don't re-root to its `root`
  field is discarded (a lying or bit-rotted manifest never offers);
- chunk files are re-hashed against the manifest on `verify()` (the
  `cli snapshot verify` path) and at restore time, so disk corruption
  after a clean write is caught before any byte reaches the app.

Chunk hashing runs through the device Merkle kernels
(`ops/merkle.leaf_hashes_jit`) when the uniform chunk shapes allow AND
the installed crypto backend actually runs the TPU rung (on a CPU-only
rig the XLA compile of a multi-KB-row SHA-256 batch costs minutes, so
those rigs keep the host loop; `TM_SNAPSHOT_DEVICE_HASH` forces either
way).  The host tree (`types/merkle`) is the differential-tested
fallback — snapshot verification is the same TPU hot path the block
pipeline uses.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from dataclasses import dataclass

from tendermint_tpu.types import merkle as hmerkle
from tendermint_tpu.utils.fail import fail_point
from tendermint_tpu.utils.metrics import REGISTRY
from tendermint_tpu.utils import log as log_mod

log = log_mod.get_logger("statesync")

SNAPSHOT_SCHEMA = "tpu-bft-snapshot/1"
SNAPSHOT_FORMAT = 1
DEFAULT_CHUNK_SIZE = 64 * 1024
DEFAULT_RETAIN = 2
MANIFEST_NAME = "manifest.json"

# below this many uniform chunks the jit dispatch costs more than the
# host loop; the differential tests pin both paths to identical hashes
_DEVICE_MIN_CHUNKS = 8


def _device_hash_enabled() -> bool:
    """Whether chunk hashing may take the jitted device kernel.  Follows
    the ambient crypto rung: on a CPU-only rig (python/native backends,
    every scenario run, this repo's CI) the XLA compile of a
    multi-KB-row SHA-256 batch costs minutes — far more than the host
    loop ever will — so the device path is reserved for rigs that
    actually run the TPU rung.  `TM_SNAPSHOT_DEVICE_HASH=1/0` forces
    either way."""
    forced = os.environ.get("TM_SNAPSHOT_DEVICE_HASH")
    if forced is not None:
        return forced not in ("0", "false", "no")
    from tendermint_tpu.crypto import backend as cb
    cur = getattr(cb, "_current", None)   # peek; don't install one
    if cur is None:
        return False
    if getattr(cur, "name", "") == "tpu":
        return True
    rungs = getattr(cur, "_rungs", None)  # supervised ladder: top rung
    return bool(rungs) and getattr(rungs[0], "name", "") == "tpu"


# -- payload ----------------------------------------------------------------

def encode_payload(state_bytes: bytes, app_state: bytes) -> bytes:
    """`lp(state) || lp(app_state)` — one blob the chunker splits."""
    return (len(state_bytes).to_bytes(4, "big") + state_bytes +
            len(app_state).to_bytes(4, "big") + app_state)


def decode_payload(payload: bytes) -> tuple[bytes, bytes]:
    if len(payload) < 4:
        raise ValueError("snapshot payload truncated (no state length)")
    n = int.from_bytes(payload[:4], "big")
    state_bytes = payload[4:4 + n]
    if len(state_bytes) != n:
        raise ValueError("snapshot payload truncated (state)")
    rest = payload[4 + n:]
    if len(rest) < 4:
        raise ValueError("snapshot payload truncated (no app length)")
    m = int.from_bytes(rest[:4], "big")
    app_state = rest[4:4 + m]
    if len(app_state) != m or len(rest) != 4 + m:
        raise ValueError("snapshot payload truncated (app state)")
    return state_bytes, app_state


def split_chunks(payload: bytes, chunk_size: int) -> list[bytes]:
    if chunk_size <= 0:
        raise ValueError(f"chunk_size must be positive, got {chunk_size}")
    if not payload:
        return [b""]
    return [payload[i:i + chunk_size]
            for i in range(0, len(payload), chunk_size)]


def hash_chunks(chunks: list[bytes]) -> list[bytes]:
    """Leaf hash per chunk.  The uniform-length prefix (every chunk but
    a possibly-short tail) goes through the batched device kernel in one
    lockstep SHA-256; the tail and any small batch hash host-side."""
    if not chunks:
        return []
    uniform = len(chunks)
    tail_len = len(chunks[-1])
    if uniform > 1 and tail_len != len(chunks[0]):
        uniform -= 1
    out: list[bytes] | None = None
    if uniform >= _DEVICE_MIN_CHUNKS and _device_hash_enabled():
        try:
            import numpy as np
            from tendermint_tpu.ops import merkle as dmerkle
            data = np.frombuffer(b"".join(chunks[:uniform]),
                                 dtype=np.uint8)
            data = data.reshape(uniform, len(chunks[0]))
            hashed = np.asarray(dmerkle.leaf_hashes_jit(data))
            out = [hashed[i].tobytes() for i in range(uniform)]
        except Exception:   # no device/jax: host fallback is exact
            log.exception("device chunk hashing failed; host fallback")
            out = None
    if out is None:
        out = [hmerkle.leaf_hash(c) for c in chunks[:uniform]]
    out.extend(hmerkle.leaf_hash(c) for c in chunks[uniform:])
    return out


def verify_chunk_hashes(chunks: dict[int, bytes],
                        expected: tuple[bytes, ...]) -> list[int]:
    """Indices whose chunk bytes do NOT hash to the manifest's
    commitment.  One batched call over everything fetched; counts land
    on the chunks_verified / chunks_rejected metrics."""
    idxs = sorted(chunks)
    hashed = hash_chunks([chunks[i] for i in idxs])
    bad = [i for i, h in zip(idxs, hashed) if h != expected[i]]
    if len(idxs) - len(bad):
        REGISTRY.chunks_verified.inc(len(idxs) - len(bad))
    if bad:
        REGISTRY.chunks_rejected.inc(len(bad))
    return bad


# -- manifest ---------------------------------------------------------------

@dataclass(frozen=True)
class SnapshotManifest:
    height: int
    format: int
    chunk_size: int
    chunk_hashes: tuple[bytes, ...]
    root: bytes
    app_hash: bytes

    @property
    def chunks(self) -> int:
        return len(self.chunk_hashes)

    def key(self) -> tuple:
        """Identity for cross-peer offer matching: two peers offering
        the same (height, format, root, app_hash) offer the same
        snapshot.  app_hash is part of the identity so a forged
        manifest that reuses honest chunks (same root) but lies about
        the app hash forms its OWN offer group — blamed on its own
        providers, never mixed into the honest group."""
        return (self.height, self.format, self.root, self.app_hash)

    def canonical_body(self) -> dict:
        return {
            "schema": SNAPSHOT_SCHEMA, "height": self.height,
            "format": self.format, "chunk_size": self.chunk_size,
            "chunk_hashes": [h.hex() for h in self.chunk_hashes],
            "root": self.root.hex(), "app_hash": self.app_hash.hex(),
        }

    def encode_json(self) -> bytes:
        body = self.canonical_body()
        raw = json.dumps(body, sort_keys=True,
                         separators=(",", ":")).encode()
        body["crc32"] = zlib.crc32(raw)
        return json.dumps(body, sort_keys=True).encode()

    @classmethod
    def decode_json(cls, raw: bytes) -> "SnapshotManifest":
        """Parse + integrity-check a manifest.  Raises ValueError on a
        torn/garbled file, a CRC mismatch, or chunk hashes that don't
        re-root to the committed root."""
        try:
            d = json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise ValueError(f"torn manifest: {e}") from None
        if not isinstance(d, dict) or d.get("schema") != SNAPSHOT_SCHEMA:
            raise ValueError(f"not a {SNAPSHOT_SCHEMA} manifest")
        crc = d.pop("crc32", None)
        canon = json.dumps(d, sort_keys=True,
                           separators=(",", ":")).encode()
        if crc != zlib.crc32(canon):
            raise ValueError("manifest crc32 mismatch (torn write)")
        m = cls(height=int(d["height"]), format=int(d["format"]),
                chunk_size=int(d["chunk_size"]),
                chunk_hashes=tuple(bytes.fromhex(h)
                                   for h in d["chunk_hashes"]),
                root=bytes.fromhex(d["root"]),
                app_hash=bytes.fromhex(d["app_hash"]))
        if hmerkle.root_from_leaf_hashes(list(m.chunk_hashes)) != m.root:
            raise ValueError("manifest chunk hashes do not re-root to "
                             "the committed root")
        return m


# -- store ------------------------------------------------------------------

class SnapshotStore:
    """Disk-backed snapshot collection with retention.

    `create()` is the only writer; every reader revalidates (manifest
    CRC + root re-check) so a torn snapshot — crash mid-create, fsck'd
    disk — is silently unavailable rather than silently wrong."""

    def __init__(self, root_dir: str,
                 chunk_size: int = DEFAULT_CHUNK_SIZE,
                 retain: int = DEFAULT_RETAIN):
        if retain < 1:
            raise ValueError(f"retain must be >= 1, got {retain}")
        self.root_dir = root_dir
        self.chunk_size = chunk_size
        self.retain = retain
        os.makedirs(root_dir, exist_ok=True)

    # -- paths ----------------------------------------------------------
    def snapshot_dir(self, height: int) -> str:
        return os.path.join(self.root_dir, f"snapshot-{height:010d}")

    @staticmethod
    def _chunk_path(sdir: str, index: int) -> str:
        return os.path.join(sdir, f"chunk-{index:06d}.bin")

    # -- create ---------------------------------------------------------
    def create(self, state, app_state: bytes) -> SnapshotManifest:
        """Snapshot `state` (a state.State at its committed height) +
        the serialized app state.  Chunks land first, the manifest last
        via tmp + atomic rename; then retention prunes old heights."""
        t0 = time.time()
        height = state.last_block_height
        if height <= 0:
            raise ValueError("cannot snapshot at height 0")
        payload = encode_payload(state.encode(), app_state)
        chunks = split_chunks(payload, self.chunk_size)
        hashes = hash_chunks(chunks)
        manifest = SnapshotManifest(
            height=height, format=SNAPSHOT_FORMAT,
            chunk_size=self.chunk_size, chunk_hashes=tuple(hashes),
            root=hmerkle.root_from_leaf_hashes(hashes),
            app_hash=state.app_hash)
        sdir = self.snapshot_dir(height)
        os.makedirs(sdir, exist_ok=True)
        for i, chunk in enumerate(chunks):
            with open(self._chunk_path(sdir, i), "wb") as f:
                f.write(chunk)
                f.flush()
                os.fsync(f.fileno())
            fail_point("Snapshot.chunkWritten")
        fail_point("Snapshot.chunksWritten")
        tmp = os.path.join(sdir, MANIFEST_NAME + ".tmp")
        with open(tmp, "wb") as f:
            f.write(manifest.encode_json())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(sdir, MANIFEST_NAME))
        self.prune_retained()
        dt = time.time() - t0
        REGISTRY.snapshots_created.inc()
        REGISTRY.snapshot_create_seconds.observe(dt)
        log.info("snapshot created", height=height,
                 chunks=len(chunks), bytes=len(payload),
                 seconds=round(dt, 3))
        return manifest

    # -- scan / load ----------------------------------------------------
    def scan(self) -> tuple[list[SnapshotManifest], list[tuple[str, str]]]:
        """(valid manifests ascending by height, [(dir, why)] rejects).
        Scanning never raises on a bad snapshot — a torn dir is evidence
        of a crash, not an error to propagate."""
        valid: list[SnapshotManifest] = []
        rejects: list[tuple[str, str]] = []
        try:
            names = sorted(os.listdir(self.root_dir))
        except FileNotFoundError:
            return [], []
        for name in names:
            sdir = os.path.join(self.root_dir, name)
            if not name.startswith("snapshot-") or not os.path.isdir(sdir):
                continue
            mpath = os.path.join(sdir, MANIFEST_NAME)
            if not os.path.exists(mpath):
                rejects.append((sdir, "no manifest (torn create)"))
                continue
            try:
                with open(mpath, "rb") as f:
                    m = SnapshotManifest.decode_json(f.read())
            except (OSError, ValueError) as e:
                rejects.append((sdir, str(e)))
                continue
            if self.snapshot_dir(m.height) != sdir:
                rejects.append((sdir, f"manifest height {m.height} does "
                                      f"not match directory name"))
                continue
            valid.append(m)
        return valid, rejects

    def list(self) -> list[SnapshotManifest]:
        return self.scan()[0]

    def best(self) -> SnapshotManifest | None:
        valid = self.list()
        return valid[-1] if valid else None

    def load_manifest(self, height: int) -> SnapshotManifest | None:
        mpath = os.path.join(self.snapshot_dir(height), MANIFEST_NAME)
        try:
            with open(mpath, "rb") as f:
                return SnapshotManifest.decode_json(f.read())
        except (OSError, ValueError):
            return None

    def load_chunk(self, height: int, index: int) -> bytes | None:
        try:
            with open(self._chunk_path(self.snapshot_dir(height), index),
                      "rb") as f:
                return f.read()
        except OSError:
            return None

    # -- verify (the `cli snapshot verify` engine) ----------------------
    def verify(self, height: int) -> dict:
        """Re-hash every chunk against the manifest.  Returns
        {height, ok, manifest_ok, chunks, bad_chunks, missing_chunks};
        `ok` only when the manifest validates AND every chunk is present
        and hashes to its commitment."""
        report = {"height": height, "ok": False, "manifest_ok": False,
                  "chunks": 0, "bad_chunks": [], "missing_chunks": []}
        m = self.load_manifest(height)
        if m is None:
            return report
        report["manifest_ok"] = True
        report["chunks"] = m.chunks
        present: dict[int, bytes] = {}
        for i in range(m.chunks):
            chunk = self.load_chunk(height, i)
            if chunk is None:
                report["missing_chunks"].append(i)
            else:
                present[i] = chunk
        report["bad_chunks"] = verify_chunk_hashes(present, m.chunk_hashes)
        report["ok"] = not (report["bad_chunks"]
                            or report["missing_chunks"])
        return report

    # -- retention ------------------------------------------------------
    def delete(self, height: int) -> None:
        sdir = self.snapshot_dir(height)
        if not os.path.isdir(sdir):
            return
        for name in os.listdir(sdir):
            try:
                os.unlink(os.path.join(sdir, name))
            except OSError:
                pass
        try:
            os.rmdir(sdir)
        except OSError:
            pass

    def prune_retained(self) -> list[int]:
        """Keep the newest `retain` VALID snapshots; drop the rest (and
        any torn directory older than the newest valid one — a torn dir
        NEWER than every valid snapshot is kept for post-mortem)."""
        valid, rejects = self.scan()
        dropped: list[int] = []
        for m in valid[:-self.retain] if len(valid) > self.retain else []:
            self.delete(m.height)
            dropped.append(m.height)
        if valid:
            newest = self.snapshot_dir(valid[-1].height)
            for sdir, _why in rejects:
                if sdir < newest:
                    for name in os.listdir(sdir):
                        try:
                            os.unlink(os.path.join(sdir, name))
                        except OSError:
                            pass
                    try:
                        os.rmdir(sdir)
                    except OSError:
                        pass
        return dropped
