"""State sync: chunked, hash-verified state snapshots + verified rejoin.

A node crashed at height N rejoins from a recent snapshot plus a short
windowed fast-sync of `snapshot_height -> tip`, instead of replaying the
whole committed prefix from genesis.  Three pieces:

- `snapshot`: the on-disk format — fixed-size chunks of the serialized
  consensus `State` + app state, a Merkle root over the chunk hashes
  (device-batched when the chunk shapes allow), a CRC-framed manifest
  written last so torn snapshots are detectable, and retention of the
  last K snapshots.
- `restore`: the offer/fetch/verify protocol — pick the best manifest
  across peers, cross-check its app_hash against a light-client-verified
  header, fetch chunks from multiple peers in parallel, verify every
  chunk hash (one batched call) before apply, and blame the serving
  peer for every mismatch (feeding p2p misbehavior scoring/bans).
- `messages`: the wire messages for a future statesync reactor
  (channel 0x60), codec-complete so rig-level protocols and the p2p
  layer share one vocabulary.
"""

from tendermint_tpu.statesync.snapshot import (DEFAULT_CHUNK_SIZE,
                                               DEFAULT_RETAIN,
                                               SNAPSHOT_FORMAT,
                                               SnapshotManifest,
                                               SnapshotStore,
                                               decode_payload,
                                               encode_payload, hash_chunks,
                                               split_chunks)
from tendermint_tpu.statesync.restore import (RestoreError, StateSyncer,
                                              StoreSource,
                                              verify_manifest_app_hash)
from tendermint_tpu.statesync.messages import STATESYNC_CHANNEL

__all__ = ["DEFAULT_CHUNK_SIZE", "DEFAULT_RETAIN", "SNAPSHOT_FORMAT",
           "STATESYNC_CHANNEL", "RestoreError", "SnapshotManifest",
           "SnapshotStore", "StateSyncer", "StoreSource",
           "decode_payload", "encode_payload", "hash_chunks",
           "split_chunks", "verify_manifest_app_hash"]
