"""Socket ABCI client: the node side of an out-of-process app.

Reference: abci socket client (`proxy/client.go:74-79`).  The node gets
three independent connections (mempool / consensus / query) so CheckTx
traffic never queues behind block execution — the same isolation the
reference's multiAppConn provides (`proxy/multi_app_conn.go:71-110`).
"""

from __future__ import annotations

import socket
import threading

from tendermint_tpu.abci import wire
from tendermint_tpu.abci.types import (RequestBeginBlock, ResponseEndBlock,
                                       ResponseInfo, ResponseQuery, Result)
from tendermint_tpu.types.codec import Reader, lp_bytes, u64
from tendermint_tpu.utils.log import get_logger

log = get_logger("abci")


class ABCIClientError(Exception):
    pass


class SocketAppConn:
    """One connection; request/response serialized by a lock.  `name`
    identifies which of the three proxy connections this is (mempool /
    consensus / query) so a dead socket's errors say which plane died."""

    def __init__(self, addr: str, timeout: float = 10.0, name: str = ""):
        assert addr.startswith("tcp://")
        self.name = name or addr
        host, port = addr[6:].rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError as e:
            # close failures can't be retried, but a socket that won't
            # even close is worth a breadcrumb when the app misbehaves
            log.debug("abci conn close failed", conn=self.name,
                      err=str(e))

    def _call(self, msg_type: int, payload: bytes = b"") -> bytes:
        try:
            with self._lock:
                wire.write_frame(self._sock, msg_type, payload)
                resp_type, resp = wire.read_frame(self._sock)
        except (OSError, EOFError) as e:
            # name the plane and the request: "consensus conn died on
            # msg 0x12" localizes an app crash to the exact call
            raise ABCIClientError(
                f"abci {self.name} connection failed on request "
                f"type {msg_type}: {type(e).__name__}: {e}") from e
        if resp_type == wire.MSG_EXCEPTION:
            raise ABCIClientError(Reader(resp).lp_bytes().decode())
        if resp_type != msg_type:
            raise ABCIClientError(
                f"response type {resp_type} != request {msg_type}")
        return resp

    # -- the AppConn interface ------------------------------------------
    def echo(self, msg: bytes) -> bytes:
        return self._call(wire.MSG_ECHO, msg)

    def info(self) -> ResponseInfo:
        return wire.decode_response_info(self._call(wire.MSG_INFO))

    def set_option(self, key: str, value: str) -> str:
        out = self._call(wire.MSG_SET_OPTION,
                         lp_bytes(key.encode()) + lp_bytes(value.encode()))
        return Reader(out).lp_bytes().decode()

    def init_chain(self, validators) -> None:
        self._call(wire.MSG_INIT_CHAIN, wire.encode_validators(validators))

    def query(self, data: bytes, path: str = "/", height: int = 0,
              prove: bool = False) -> ResponseQuery:
        return wire.decode_response_query(self._call(
            wire.MSG_QUERY,
            wire.encode_request_query(data, path, height, prove)))

    def begin_block(self, req: RequestBeginBlock) -> None:
        self._call(wire.MSG_BEGIN_BLOCK, wire.encode_request_begin_block(req))

    def check_tx(self, tx: bytes) -> Result:
        return Result.decode(Reader(self._call(wire.MSG_CHECK_TX,
                                               lp_bytes(tx))))

    def deliver_tx(self, tx: bytes) -> Result:
        return Result.decode(Reader(self._call(wire.MSG_DELIVER_TX,
                                               lp_bytes(tx))))

    def end_block(self, height: int) -> ResponseEndBlock:
        return wire.decode_response_end_block(
            self._call(wire.MSG_END_BLOCK, u64(height)))

    def commit(self) -> Result:
        return Result.decode(Reader(self._call(wire.MSG_COMMIT)))


def new_socket_app_conns(addr: str):
    """Three sockets to one app server (mempool / consensus / query)."""
    from tendermint_tpu.proxy import AppConns
    return AppConns(mempool=SocketAppConn(addr, name="mempool"),
                    consensus=SocketAppConn(addr, name="consensus"),
                    query=SocketAppConn(addr, name="query"))
