"""ABCI application base class and the in-proc app registry.

Reference: the abci repo's Application interface (CheckTx / DeliverTx /
BeginBlock / EndBlock / Commit / Query / Info / InitChain) plus the
in-proc client creator table (`proxy/client.go:65-79` — `dummy`,
`persistent_dummy`, `counter`, `nilapp`).
"""

from __future__ import annotations

from tendermint_tpu.abci.types import (OK, RequestBeginBlock, ResponseEndBlock,
                                       ResponseInfo, ResponseQuery, Result)


class Application:
    """Override what you need; defaults are no-ops that accept everything."""

    def info(self) -> ResponseInfo:
        return ResponseInfo()

    def set_option(self, key: str, value: str) -> str:
        return ""

    def init_chain(self, validators: list) -> None:
        pass

    def query(self, data: bytes, path: str = "/", height: int = 0,
              prove: bool = False) -> ResponseQuery:
        return ResponseQuery(code=OK)

    def check_tx(self, tx: bytes) -> Result:
        return Result(OK)

    def begin_block(self, req: RequestBeginBlock) -> None:
        pass

    def deliver_tx(self, tx: bytes) -> Result:
        return Result(OK)

    def end_block(self, height: int) -> ResponseEndBlock:
        return ResponseEndBlock()

    def commit(self) -> Result:
        """Returns the new app hash in `data`."""
        return Result(OK)

    # -- state sync (statesync/ snapshot plane) -------------------------
    # Modeled on ABCI's ListSnapshots/ApplySnapshotChunk pair, collapsed
    # to one blob: the statesync layer owns chunking and verification,
    # the app only (de)serializes its full state.  Apps that don't
    # override these are not snapshottable (`supports_snapshots()` is
    # how callers gate snapshot creation).

    def snapshot_state(self) -> bytes:
        """Serialize the full app state at the current committed height."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support state snapshots")

    def restore_state(self, data: bytes) -> None:
        """Replace the app state with a previously serialized snapshot."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support state snapshots")

    def supports_snapshots(self) -> bool:
        return type(self).snapshot_state is not Application.snapshot_state


_REGISTRY: dict[str, type] = {}


def register_app(name: str, cls: type) -> None:
    _REGISTRY[name] = cls


def create_app(name: str) -> Application:
    """In-proc app by name (reference `proxy/client.go:65-79`)."""
    from tendermint_tpu.abci.apps import counter, kvstore  # noqa: F401 - registers
    if name not in _REGISTRY:
        raise ValueError(f"unknown in-proc app {name!r}; "
                         f"known: {sorted(_REGISTRY)}")
    return _REGISTRY[name]()
