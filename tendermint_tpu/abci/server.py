"""Socket ABCI server: exposes an Application to an external node process.

Reference: the abci repo's socket server (used when the app runs
out-of-process, `proxy/client.go:74-79`).  One thread per connection;
requests on a connection are served strictly in order.  The app itself is
guarded by one lock shared across connections, matching the in-proc
semantics in `tendermint_tpu.proxy`.
"""

from __future__ import annotations

import socket
import threading

from tendermint_tpu.abci import wire
from tendermint_tpu.abci.app import Application
from tendermint_tpu.types.codec import Reader, lp_bytes, u64


class ABCIServer:
    def __init__(self, app: Application, addr: str = "tcp://127.0.0.1:26658"):
        assert addr.startswith("tcp://")
        host, port = addr[6:].rsplit(":", 1)
        self.app = app
        self.host, self.port = host, int(port)
        self._app_lock = threading.Lock()
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._stopped = threading.Event()

    def start(self) -> None:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self.port = self._listener.getsockname()[1]   # resolve port 0
        self._listener.listen(8)
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="abci-accept")
        t.start()
        self._threads.append(t)

    @property
    def addr(self) -> str:
        return f"tcp://{self.host}:{self.port}"

    def stop(self) -> None:
        self._stopped.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="abci-conn")
            t.start()
            self._threads.append(t)

    def _serve(self, conn: socket.socket) -> None:
        try:
            while not self._stopped.is_set():
                msg_type, payload = wire.read_frame(conn)
                resp_type, resp = self._dispatch(msg_type, payload)
                wire.write_frame(conn, resp_type, resp)
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def _dispatch(self, msg_type: int, payload: bytes) -> tuple[int, bytes]:
        return dispatch(self.app, self._app_lock, msg_type, payload)


def dispatch(app: Application, app_lock: threading.Lock, msg_type: int,
             payload: bytes) -> tuple[int, bytes]:
    """Decode one ABCI request, run it on the app under its lock, encode
    the response — shared by the socket server and the gRPC server
    (reference apps attach over either transport, proxy/client.go:65-79)."""
    try:
        with app_lock:
            if msg_type == wire.MSG_ECHO:
                return msg_type, payload
            if msg_type == wire.MSG_INFO:
                return msg_type, wire.encode_response_info(app.info())
            if msg_type == wire.MSG_SET_OPTION:
                r = Reader(payload)
                out = app.set_option(r.lp_bytes().decode(),
                                     r.lp_bytes().decode())
                return msg_type, lp_bytes(out.encode())
            if msg_type == wire.MSG_INIT_CHAIN:
                vals = wire.decode_validators(Reader(payload))
                app.init_chain(vals)
                return msg_type, b""
            if msg_type == wire.MSG_QUERY:
                data, path, height, prove = wire.decode_request_query(
                    payload)
                return msg_type, wire.encode_response_query(
                    app.query(data, path, height, prove))
            if msg_type == wire.MSG_BEGIN_BLOCK:
                app.begin_block(wire.decode_request_begin_block(payload))
                return msg_type, b""
            if msg_type == wire.MSG_CHECK_TX:
                return msg_type, app.check_tx(
                    Reader(payload).lp_bytes()).encode()
            if msg_type == wire.MSG_DELIVER_TX:
                return msg_type, app.deliver_tx(
                    Reader(payload).lp_bytes()).encode()
            if msg_type == wire.MSG_END_BLOCK:
                height = Reader(payload).u64()
                return msg_type, wire.encode_response_end_block(
                    app.end_block(height))
            if msg_type == wire.MSG_COMMIT:
                return msg_type, app.commit().encode()
        return wire.MSG_EXCEPTION, lp_bytes(
            b"unknown message type %d" % msg_type)
    except Exception as e:  # app errors must not kill the server
        return wire.MSG_EXCEPTION, lp_bytes(str(e).encode())
