"""kvstore ("dummy") app: the reference's default test application.

Reference: abci example dummy app (used via `--proxy_app=dummy`,
`proxy/client.go:65-73`): txs are `key=value` (or `value` meaning
`value=value`); state is a map; app hash commits to the contents.
Persistent variant stores to disk and survives restarts, reporting its
last height in Info for handshake replay.
"""

from __future__ import annotations

import hashlib
import json
import os

from tendermint_tpu.abci.app import Application, register_app
from tendermint_tpu.abci.types import (OK, ResponseInfo,
                                       ResponseQuery, Result)


N_BUCKETS = 256


class KVStoreApp(Application):
    def __init__(self):
        self.state: dict[bytes, bytes] = {}
        self.height = 0
        # incremental state commitment: keys shard into 256 buckets by
        # key digest; a write re-hashes only its bucket (O(state/256))
        # and the app hash roots the bucket digests.  A full sorted
        # re-hash per commit is O(state) and turns long replays
        # quadratic (the reference dummy app's merkle tree is
        # incremental for the same reason); plain XOR/sum accumulators
        # are LINEAR and therefore forgeable — nested sha256 is not.
        self._buckets: list[dict[bytes, bytes]] = [
            {} for _ in range(N_BUCKETS)]
        self._bucket_digest = [bytes(32)] * N_BUCKETS

    def _set(self, k: bytes, v: bytes) -> None:
        b = hashlib.sha256(k).digest()[0]
        self.state[k] = v
        self._buckets[b][k] = v
        self._rehash_bucket(b)

    def _rehash_bucket(self, b: int) -> None:
        bucket = self._buckets[b]
        h = hashlib.sha256()
        for bk in sorted(bucket):
            bv = bucket[bk]
            h.update(len(bk).to_bytes(4, "big") + bk)
            h.update(len(bv).to_bytes(4, "big") + bv)
        self._bucket_digest[b] = h.digest()

    def _app_hash(self) -> bytes:
        return hashlib.sha256(
            b"".join(self._bucket_digest) +
            self.height.to_bytes(8, "big")).digest()[:20]

    def info(self) -> ResponseInfo:
        return ResponseInfo(data=f"{{\"size\":{len(self.state)}}}",
                            last_block_height=self.height,
                            last_block_app_hash=(self._app_hash()
                                                 if self.height else b""))

    def check_tx(self, tx: bytes) -> Result:
        return Result(OK)

    def deliver_tx(self, tx: bytes) -> Result:
        if b"=" in tx:
            k, v = tx.split(b"=", 1)
        else:
            k = v = tx
        self._set(k, v)
        return Result(OK)

    def end_block(self, height: int):
        from tendermint_tpu.abci.types import ResponseEndBlock
        return ResponseEndBlock()

    def commit(self) -> Result:
        self.height += 1
        return Result(OK, data=self._app_hash())

    def query(self, data: bytes, path: str = "/", height: int = 0,
              prove: bool = False) -> ResponseQuery:
        v = self.state.get(data)
        if v is None:
            return ResponseQuery(code=OK, key=data, log="does not exist",
                                 height=self.height)
        return ResponseQuery(code=OK, key=data, value=v, log="exists",
                             height=self.height)

    # -- state sync -----------------------------------------------------
    def snapshot_state(self) -> bytes:
        """Full state as u64(height) || (lp(k) || lp(v))* sorted by key —
        deterministic, so two nodes at the same height serialize the
        identical blob (and the identical snapshot chunk hashes)."""
        out = [self.height.to_bytes(8, "big")]
        for k in sorted(self.state):
            v = self.state[k]
            out.append(len(k).to_bytes(4, "big") + k)
            out.append(len(v).to_bytes(4, "big") + v)
        return b"".join(out)

    def restore_state(self, data: bytes) -> None:
        """Rebuild from a snapshot blob.  Buckets are filled first and
        digested ONCE each: restoring through `_set` would re-hash each
        growing bucket per key — O(state²/256), i.e. as slow as replaying
        every tx, which defeats the point of a snapshot."""
        height = int.from_bytes(data[:8], "big")
        off, n = 8, len(data)
        state: dict[bytes, bytes] = {}
        while off < n:
            klen = int.from_bytes(data[off:off + 4], "big")
            k = data[off + 4:off + 4 + klen]
            off += 4 + klen
            vlen = int.from_bytes(data[off:off + 4], "big")
            v = data[off + 4:off + 4 + vlen]
            off += 4 + vlen
            if len(k) != klen or len(v) != vlen:
                raise ValueError("truncated kvstore snapshot blob")
            state[k] = v
        self.state = state
        self.height = height
        self._buckets = [{} for _ in range(N_BUCKETS)]
        self._bucket_digest = [bytes(32)] * N_BUCKETS
        for k, v in state.items():
            self._buckets[hashlib.sha256(k).digest()[0]][k] = v
        for b in range(N_BUCKETS):
            if self._buckets[b]:
                self._rehash_bucket(b)


class PersistentKVStoreApp(KVStoreApp):
    """Disk-backed variant (reference `persistent_dummy`): used by crash
    tests — Info() reports the persisted height for handshake replay."""

    def __init__(self, db_path: str | None = None):
        super().__init__()
        self.db_path = db_path or os.environ.get(
            "TM_KVSTORE_PATH", "kvstore_app.json")
        self._load()

    def _load(self):
        if os.path.exists(self.db_path):
            with open(self.db_path) as f:
                d = json.load(f)
            self.height = d["height"]
            # bucket-first load, one digest pass per bucket (same
            # reasoning as restore_state: per-key _set is quadratic)
            for k, v in d["state"].items():
                kb, vb = bytes.fromhex(k), bytes.fromhex(v)
                self.state[kb] = vb
                self._buckets[hashlib.sha256(kb).digest()[0]][kb] = vb
            for b in range(N_BUCKETS):
                if self._buckets[b]:
                    self._rehash_bucket(b)

    def commit(self) -> Result:
        res = super().commit()
        self.persist_state()
        return res

    def persist_state(self) -> None:
        """Write the current state to disk (tmp + fsync + rename).
        Commit's persistence step, also called directly after a
        snapshot restore_state (which bypasses commit)."""
        tmp = self.db_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"height": self.height,
                       "state": {k.hex(): v.hex()
                                 for k, v in self.state.items()}}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.db_path)


register_app("kvstore", KVStoreApp)
register_app("dummy", KVStoreApp)
register_app("persistent_kvstore", PersistentKVStoreApp)
register_app("persistent_dummy", PersistentKVStoreApp)
register_app("nilapp", Application)
