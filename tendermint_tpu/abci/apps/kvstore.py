"""kvstore ("dummy") app: the reference's default test application.

Reference: abci example dummy app (used via `--proxy_app=dummy`,
`proxy/client.go:65-73`): txs are `key=value` (or `value` meaning
`value=value`); state is a map; app hash commits to the contents.
Persistent variant stores to disk and survives restarts, reporting its
last height in Info for handshake replay.
"""

from __future__ import annotations

import hashlib
import json
import os

from tendermint_tpu.abci.app import Application, register_app
from tendermint_tpu.abci.types import (OK, ResponseInfo,
                                       ResponseQuery, Result)


N_BUCKETS = 256


class KVStoreApp(Application):
    def __init__(self):
        self.state: dict[bytes, bytes] = {}
        self.height = 0
        # incremental state commitment: keys shard into 256 buckets by
        # key digest; a write re-hashes only its bucket (O(state/256))
        # and the app hash roots the bucket digests.  A full sorted
        # re-hash per commit is O(state) and turns long replays
        # quadratic (the reference dummy app's merkle tree is
        # incremental for the same reason); plain XOR/sum accumulators
        # are LINEAR and therefore forgeable — nested sha256 is not.
        self._buckets: list[dict[bytes, bytes]] = [
            {} for _ in range(N_BUCKETS)]
        self._bucket_digest = [bytes(32)] * N_BUCKETS

    def _set(self, k: bytes, v: bytes) -> None:
        b = hashlib.sha256(k).digest()[0]
        self.state[k] = v
        bucket = self._buckets[b]
        bucket[k] = v
        h = hashlib.sha256()
        for bk in sorted(bucket):
            bv = bucket[bk]
            h.update(len(bk).to_bytes(4, "big") + bk)
            h.update(len(bv).to_bytes(4, "big") + bv)
        self._bucket_digest[b] = h.digest()

    def _app_hash(self) -> bytes:
        return hashlib.sha256(
            b"".join(self._bucket_digest) +
            self.height.to_bytes(8, "big")).digest()[:20]

    def info(self) -> ResponseInfo:
        return ResponseInfo(data=f"{{\"size\":{len(self.state)}}}",
                            last_block_height=self.height,
                            last_block_app_hash=(self._app_hash()
                                                 if self.height else b""))

    def check_tx(self, tx: bytes) -> Result:
        return Result(OK)

    def deliver_tx(self, tx: bytes) -> Result:
        if b"=" in tx:
            k, v = tx.split(b"=", 1)
        else:
            k = v = tx
        self._set(k, v)
        return Result(OK)

    def end_block(self, height: int):
        from tendermint_tpu.abci.types import ResponseEndBlock
        return ResponseEndBlock()

    def commit(self) -> Result:
        self.height += 1
        return Result(OK, data=self._app_hash())

    def query(self, data: bytes, path: str = "/", height: int = 0,
              prove: bool = False) -> ResponseQuery:
        v = self.state.get(data)
        if v is None:
            return ResponseQuery(code=OK, key=data, log="does not exist",
                                 height=self.height)
        return ResponseQuery(code=OK, key=data, value=v, log="exists",
                             height=self.height)


class PersistentKVStoreApp(KVStoreApp):
    """Disk-backed variant (reference `persistent_dummy`): used by crash
    tests — Info() reports the persisted height for handshake replay."""

    def __init__(self, db_path: str | None = None):
        super().__init__()
        self.db_path = db_path or os.environ.get(
            "TM_KVSTORE_PATH", "kvstore_app.json")
        self._load()

    def _load(self):
        if os.path.exists(self.db_path):
            with open(self.db_path) as f:
                d = json.load(f)
            self.height = d["height"]
            for k, v in d["state"].items():
                self._set(bytes.fromhex(k), bytes.fromhex(v))

    def commit(self) -> Result:
        res = super().commit()
        tmp = self.db_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"height": self.height,
                       "state": {k.hex(): v.hex()
                                 for k, v in self.state.items()}}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.db_path)
        return res


register_app("kvstore", KVStoreApp)
register_app("dummy", KVStoreApp)
register_app("persistent_kvstore", PersistentKVStoreApp)
register_app("persistent_dummy", PersistentKVStoreApp)
register_app("nilapp", Application)
