"""Counter app: ordered-nonce test application.

Reference: abci example counter (used in serial-tx tests,
`consensus/common_test.go:26-27`): with serial mode on, tx N must be the
big-endian encoding of N; CheckTx enforces nonce >= count, DeliverTx
enforces nonce == count.
"""

from __future__ import annotations

from tendermint_tpu.abci.app import Application, register_app
from tendermint_tpu.abci.types import (ERR_BAD_NONCE, ERR_ENCODING, OK,
                                       ResponseInfo, ResponseQuery, Result)


class CounterApp(Application):
    def __init__(self, serial: bool = False):
        self.serial = serial
        self.hash_count = 0
        self.tx_count = 0

    def info(self) -> ResponseInfo:
        return ResponseInfo(
            data=f"{{\"hashes\":{self.hash_count},\"txs\":{self.tx_count}}}")

    def set_option(self, key: str, value: str) -> str:
        if key == "serial":
            self.serial = value == "on"
            return "ok"
        return ""

    def _nonce(self, tx: bytes) -> int | None:
        if len(tx) > 8:
            return None
        return int.from_bytes(tx, "big")

    def check_tx(self, tx: bytes) -> Result:
        if self.serial:
            n = self._nonce(tx)
            if n is None:
                return Result(ERR_ENCODING, log="tx too long")
            if n < self.tx_count:
                return Result(ERR_BAD_NONCE,
                              log=f"nonce {n} < count {self.tx_count}")
        return Result(OK)

    def deliver_tx(self, tx: bytes) -> Result:
        if self.serial:
            n = self._nonce(tx)
            if n is None:
                return Result(ERR_ENCODING, log="tx too long")
            if n != self.tx_count:
                return Result(ERR_BAD_NONCE,
                              log=f"nonce {n} != count {self.tx_count}")
        self.tx_count += 1
        return Result(OK)

    def commit(self) -> Result:
        self.hash_count += 1
        if self.tx_count == 0:
            return Result(OK)
        return Result(OK, data=self.tx_count.to_bytes(8, "big"))

    def query(self, data: bytes, path: str = "/", height: int = 0,
              prove: bool = False) -> ResponseQuery:
        if path == "/tx":
            return ResponseQuery(code=OK, value=str(self.tx_count).encode())
        return ResponseQuery(code=OK, value=str(self.hash_count).encode())


register_app("counter", CounterApp)
register_app("counter_serial", lambda: CounterApp(serial=True))
