"""ABCI protocol types: the app <-> consensus contract.

Reference: abci v0.5.0 (`glide.yaml:21-25`) — Info / InitChain / Query /
BeginBlock / CheckTx / DeliverTx / EndBlock / Commit with result codes.
Kept as plain dataclasses; the socket protocol frames them with the codec
(`tendermint_tpu.abci.wire`).
"""

from __future__ import annotations

from dataclasses import dataclass, field

OK = 0
ERR_ENCODING = 1
ERR_BAD_NONCE = 2
ERR_BAD_SIG = 3
# admission-control rejection (mempool/mempool.py): the pool (or the
# verify plane feeding it) is at capacity and the tx did not outrank
# anything evictable — a LOAD signal, not a verdict on the tx, so
# clients may back off and resubmit (the hash is NOT cached)
ERR_MEMPOOL_FULL = 4
ERR_UNKNOWN = 99


@dataclass
class Result:
    """Outcome of CheckTx/DeliverTx (reference abci Result)."""
    code: int = OK
    data: bytes = b""
    log: str = ""

    @property
    def is_ok(self) -> bool:
        return self.code == OK

    def encode(self) -> bytes:
        from tendermint_tpu.types.codec import lp_bytes, u32
        return u32(self.code) + lp_bytes(self.data) + lp_bytes(
            self.log.encode())

    @classmethod
    def decode(cls, r) -> "Result":
        return cls(code=r.u32(), data=r.lp_bytes(), log=r.lp_bytes().decode())


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    last_block_height: int = 0
    last_block_app_hash: bytes = b""


@dataclass
class ResponseQuery:
    code: int = OK
    index: int = -1
    key: bytes = b""
    value: bytes = b""
    proof: bytes = b""
    height: int = 0
    log: str = ""


@dataclass
class Validator:
    """Validator diff in EndBlock (pub_key, power); power 0 removes."""
    pub_key: bytes
    power: int


@dataclass
class ResponseEndBlock:
    diffs: list[Validator] = field(default_factory=list)


@dataclass
class RequestBeginBlock:
    hash: bytes
    header: object  # types.Header
