"""Socket ABCI framing: length-prefixed request/response records.

Reference: the abci repo's socket protocol (varint-prefixed protobuf).
This framework frames with the deterministic codec instead: every message
is u32(len) || u8(msg_type) || payload.  One request, one response, in
order, per connection — the node opens three connections (mempool /
consensus / query) so the pipelines never block each other (reference
`proxy/multi_app_conn.go:71-110`).
"""

from __future__ import annotations

import socket
import struct

from tendermint_tpu.abci.types import (RequestBeginBlock, ResponseEndBlock,
                                       ResponseInfo, ResponseQuery,
                                       Validator)
from tendermint_tpu.types.block import Header
from tendermint_tpu.types.codec import Reader, i64, lp_bytes, u32, u64, u8

# message types (request and response share the type byte)
MSG_ECHO = 0x01
MSG_INFO = 0x02
MSG_SET_OPTION = 0x03
MSG_INIT_CHAIN = 0x04
MSG_QUERY = 0x05
MSG_BEGIN_BLOCK = 0x06
MSG_CHECK_TX = 0x07
MSG_DELIVER_TX = 0x08
MSG_END_BLOCK = 0x09
MSG_COMMIT = 0x0A
MSG_EXCEPTION = 0x3F


def write_frame(sock: socket.socket, msg_type: int, payload: bytes) -> None:
    sock.sendall(struct.pack(">IB", len(payload) + 1, msg_type) + payload)


def read_frame(sock: socket.socket) -> tuple[int, bytes]:
    hdr = _read_exact(sock, 5)
    ln, msg_type = struct.unpack(">IB", hdr)
    payload = _read_exact(sock, ln - 1)
    return msg_type, payload


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("abci connection closed")
        buf += chunk
    return buf


# -- payload codecs --------------------------------------------------------

def encode_response_info(r: ResponseInfo) -> bytes:
    return (lp_bytes(r.data.encode()) + lp_bytes(r.version.encode()) +
            u64(r.last_block_height) + lp_bytes(r.last_block_app_hash))


def decode_response_info(b: bytes) -> ResponseInfo:
    r = Reader(b)
    return ResponseInfo(data=r.lp_bytes().decode(),
                        version=r.lp_bytes().decode(),
                        last_block_height=r.u64(),
                        last_block_app_hash=r.lp_bytes())


def encode_response_query(q: ResponseQuery) -> bytes:
    return (u32(q.code) + i64(q.index) + lp_bytes(q.key) +
            lp_bytes(q.value) + lp_bytes(q.proof) + u64(q.height) +
            lp_bytes(q.log.encode()))


def decode_response_query(b: bytes) -> ResponseQuery:
    r = Reader(b)
    return ResponseQuery(code=r.u32(), index=r.i64(), key=r.lp_bytes(),
                         value=r.lp_bytes(), proof=r.lp_bytes(),
                         height=r.u64(), log=r.lp_bytes().decode())


def encode_request_query(data: bytes, path: str, height: int,
                         prove: bool) -> bytes:
    return (lp_bytes(data) + lp_bytes(path.encode()) + u64(height) +
            u8(1 if prove else 0))


def decode_request_query(b: bytes) -> tuple:
    r = Reader(b)
    return r.lp_bytes(), r.lp_bytes().decode(), r.u64(), bool(r.u8())


def encode_validators(vals: list[Validator]) -> bytes:
    out = u32(len(vals))
    for v in vals:
        out += lp_bytes(v.pub_key) + i64(v.power)
    return out


def decode_validators(r: Reader) -> list[Validator]:
    return [Validator(pub_key=r.lp_bytes(), power=r.i64())
            for _ in range(r.u32())]


def encode_request_begin_block(req: RequestBeginBlock) -> bytes:
    return lp_bytes(req.hash) + lp_bytes(req.header.encode())


def decode_request_begin_block(b: bytes) -> RequestBeginBlock:
    r = Reader(b)
    h = r.lp_bytes()
    header = Header.decode(Reader(r.lp_bytes()))
    return RequestBeginBlock(hash=h, header=header)


def encode_response_end_block(e: ResponseEndBlock) -> bytes:
    return encode_validators(e.diffs)


def decode_response_end_block(b: bytes) -> ResponseEndBlock:
    return ResponseEndBlock(diffs=decode_validators(Reader(b)))
