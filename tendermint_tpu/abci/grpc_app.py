"""ABCI over gRPC: the reference's second out-of-process app transport.

Reference: `proxy/client.go:75-79` — `NewGRPCClient` lets an app attach
over gRPC instead of the ordered socket protocol.  Here the transport is
real gRPC (HTTP/2, grpcio generic handlers — the same machinery as
`rpc/grpc_server.py`); request/response bodies reuse the framework's
deterministic ABCI wire codecs (`abci/wire.py`), so both transports share
one payload format and one server-side dispatch (`abci/server.dispatch`).

Method surface: /tendermint_tpu.ABCIApplication/<Name> with Name one of
Echo, Info, SetOption, InitChain, Query, BeginBlock, CheckTx, DeliverTx,
EndBlock, Commit.  Errors travel as gRPC aborts with the app's message.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor

from tendermint_tpu.abci import wire
from tendermint_tpu.abci.app import Application
from tendermint_tpu.abci.server import dispatch
from tendermint_tpu.abci.types import (RequestBeginBlock, ResponseEndBlock,
                                       ResponseInfo, ResponseQuery, Result)
from tendermint_tpu.types.codec import Reader, lp_bytes, u64
from tendermint_tpu.utils.log import get_logger

log = get_logger("abci-grpc")

SERVICE = "tendermint_tpu.ABCIApplication"

_METHODS = {
    "Echo": wire.MSG_ECHO,
    "Info": wire.MSG_INFO,
    "SetOption": wire.MSG_SET_OPTION,
    "InitChain": wire.MSG_INIT_CHAIN,
    "Query": wire.MSG_QUERY,
    "BeginBlock": wire.MSG_BEGIN_BLOCK,
    "CheckTx": wire.MSG_CHECK_TX,
    "DeliverTx": wire.MSG_DELIVER_TX,
    "EndBlock": wire.MSG_END_BLOCK,
    "Commit": wire.MSG_COMMIT,
}


def _ident(b: bytes) -> bytes:
    return b


class GRPCABCIServer:
    """Serves an Application over gRPC (the app-process side)."""

    def __init__(self, app: Application, laddr: str = "tcp://127.0.0.1:0"):
        import grpc
        self.app = app
        self._app_lock = threading.Lock()
        addr = laddr.replace("grpc://", "").replace("tcp://", "")
        self._server = grpc.server(ThreadPoolExecutor(8))
        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                name = handler_call_details.method.rsplit("/", 1)[-1]
                msg_type = _METHODS.get(name)
                if (msg_type is None or not
                        handler_call_details.method.startswith(
                            f"/{SERVICE}/")):
                    return None
                return grpc.unary_unary_rpc_method_handler(
                    lambda req, ctx, mt=msg_type: outer._call(mt, req, ctx),
                    request_deserializer=_ident,
                    response_serializer=_ident)

        self._server.add_generic_rpc_handlers((Handler(),))
        self._port = self._server.add_insecure_port(addr)
        host = addr.rsplit(":", 1)[0]
        self.addr = f"grpc://{host}:{self._port}"

    def _call(self, msg_type: int, payload: bytes, ctx) -> bytes:
        resp_type, resp = dispatch(self.app, self._app_lock, msg_type,
                                   payload)
        if resp_type == wire.MSG_EXCEPTION:
            import grpc
            ctx.abort(grpc.StatusCode.INTERNAL,
                      Reader(resp).lp_bytes().decode())
        return resp

    def start(self) -> None:
        self._server.start()
        log.info("abci app serving over grpc", addr=self.addr)

    def stop(self) -> None:
        self._server.stop(grace=0.5)


class GRPCAppConn:
    """Node-side connection to a gRPC app — the AppConn interface
    (reference `proxy/client.go:75-79` NewGRPCClient).  Three of these
    share one HTTP/2 channel; the server's app lock serializes."""

    def __init__(self, channel, timeout: float = 10.0):
        # same deadline discipline as the socket transport
        # (abci/client.py): a hung app must surface as an error, not
        # wedge the consensus/mempool threads forever
        self._timeout = timeout
        self._fns = {
            name: channel.unary_unary(f"/{SERVICE}/{name}",
                                      request_serializer=_ident,
                                      response_deserializer=_ident)
            for name in _METHODS
        }

    def _call(self, name: str, payload: bytes = b"") -> bytes:
        import grpc
        from tendermint_tpu.abci.client import ABCIClientError
        try:
            return self._fns[name](payload, timeout=self._timeout)
        except grpc.RpcError as e:
            raise ABCIClientError(e.details() if hasattr(e, "details")
                                  else str(e)) from None

    # -- the AppConn interface ------------------------------------------
    def echo(self, msg: bytes) -> bytes:
        return self._call("Echo", msg)

    def info(self) -> ResponseInfo:
        return wire.decode_response_info(self._call("Info"))

    def set_option(self, key: str, value: str) -> str:
        out = self._call("SetOption",
                         lp_bytes(key.encode()) + lp_bytes(value.encode()))
        return Reader(out).lp_bytes().decode()

    def init_chain(self, validators) -> None:
        self._call("InitChain", wire.encode_validators(validators))

    def query(self, data: bytes, path: str = "/", height: int = 0,
              prove: bool = False) -> ResponseQuery:
        return wire.decode_response_query(self._call(
            "Query", wire.encode_request_query(data, path, height, prove)))

    def begin_block(self, req: RequestBeginBlock) -> None:
        self._call("BeginBlock", wire.encode_request_begin_block(req))

    def check_tx(self, tx: bytes) -> Result:
        return Result.decode(Reader(self._call("CheckTx", lp_bytes(tx))))

    def deliver_tx(self, tx: bytes) -> Result:
        return Result.decode(Reader(self._call("DeliverTx", lp_bytes(tx))))

    def end_block(self, height: int) -> ResponseEndBlock:
        return wire.decode_response_end_block(
            self._call("EndBlock", u64(height)))

    def commit(self) -> Result:
        return Result.decode(Reader(self._call("Commit")))


def new_grpc_app_conns(addr: str):
    """Three logical connections to one gRPC app (mempool / consensus /
    query) multiplexed on one HTTP/2 channel."""
    import grpc
    from tendermint_tpu.proxy import AppConns
    target = addr.replace("grpc://", "")
    channel = grpc.insecure_channel(target)
    return AppConns(mempool=GRPCAppConn(channel),
                    consensus=GRPCAppConn(channel),
                    query=GRPCAppConn(channel))
