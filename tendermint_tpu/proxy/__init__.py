"""App connections: typed gateways from node components to one ABCI app.

Reference: `proxy/` — three narrowed connections (mempool / consensus /
query) to a single app (`proxy/app_conn.go:11-40`,
`proxy/multi_app_conn.go:12-28`) so mempool CheckTx never contends with
consensus DeliverTx, plus a ClientCreator choosing in-proc vs remote
socket apps (`proxy/client.go:65-79`).

In-proc apps are not thread-safe, so all three conns share one lock —
the same serialization the reference's local client mutex provides.
Remote socket apps (`tendermint_tpu.abci.server/client`) get one socket
per conn like the reference.
"""

from __future__ import annotations

import contextlib
import threading

from tendermint_tpu.abci.app import Application, create_app


class AppConn:
    """One logical connection; serializes calls with the shared lock."""

    def __init__(self, app: Application, lock: threading.Lock):
        self._app = app
        self._lock = lock

    @contextlib.contextmanager
    def batched(self):
        """Hold the conn lock across a WINDOW of calls, yielding the raw
        app (whose methods mirror this conn's, minus the per-call lock).
        `execution.apply_window` uses this to amortize B x ~4 lock
        round-trips per fast-sync window into one acquisition; remote
        socket/grpc conns don't offer it (callers feature-detect with
        getattr and fall back to per-call locking)."""
        with self._lock:
            yield self._app

    def info(self):
        with self._lock:
            return self._app.info()

    def set_option(self, key, value):
        with self._lock:
            return self._app.set_option(key, value)

    def init_chain(self, validators):
        with self._lock:
            return self._app.init_chain(validators)

    def query(self, data, path="/", height=0, prove=False):
        with self._lock:
            return self._app.query(data, path, height, prove)

    def check_tx(self, tx):
        with self._lock:
            return self._app.check_tx(tx)

    def begin_block(self, req):
        with self._lock:
            return self._app.begin_block(req)

    def deliver_tx(self, tx):
        with self._lock:
            return self._app.deliver_tx(tx)

    def end_block(self, height):
        with self._lock:
            return self._app.end_block(height)

    def commit(self):
        with self._lock:
            return self._app.commit()


class AppConns:
    """The three typed connections (reference `proxy/multi_app_conn.go`)."""

    def __init__(self, mempool: AppConn, consensus: AppConn, query: AppConn):
        self.mempool = mempool
        self.consensus = consensus
        self.query = query


class ClientCreator:
    """Creates AppConns for an app spec (reference `proxy/client.go`).

    spec: in-proc registry name ("kvstore", "counter", ...) or
    "tcp://host:port" for a remote socket app, or an Application instance.
    """

    def __init__(self, spec):
        self.spec = spec

    def new_app_conns(self) -> AppConns:
        if isinstance(self.spec, Application):
            app = self.spec
        elif isinstance(self.spec, str) and self.spec.startswith("tcp://"):
            from tendermint_tpu.abci.client import new_socket_app_conns
            return new_socket_app_conns(self.spec)
        elif isinstance(self.spec, str) and self.spec.startswith("grpc://"):
            # ABCI over gRPC (reference proxy/client.go:75-79)
            from tendermint_tpu.abci.grpc_app import new_grpc_app_conns
            return new_grpc_app_conns(self.spec)
        else:
            app = create_app(self.spec)
        lock = threading.Lock()
        return AppConns(AppConn(app, lock), AppConn(app, lock),
                        AppConn(app, lock))
