"""Deterministic binary codec for consensus-critical serialization.

The reference signs canonical JSON produced by reflection (reference
`types/canonical_json.go:44-58`, go-wire).  This framework is not
wire-compatible with Tendermint; it defines its own *fixed-layout* binary
encoding so that (a) any two nodes produce bit-identical bytes for the same
value and (b) the hot records (vote sign-bytes) have static width and can be
reconstructed device-side without per-item host serialization.

Conventions: big-endian fixed-width integers, u32 length prefixes for
variable bytes, version byte first in every top-level record.  Encoders are
pure functions bytes-in/bytes-out; decoding is only needed host-side.
"""

from __future__ import annotations

import struct

CODEC_VERSION = 1


def u8(x: int) -> bytes:
    return struct.pack(">B", x)


def u32(x: int) -> bytes:
    return struct.pack(">I", x)


def u64(x: int) -> bytes:
    return struct.pack(">Q", x)


def i64(x: int) -> bytes:
    return struct.pack(">q", x)


def lp_bytes(b: bytes) -> bytes:
    """Length-prefixed variable bytes."""
    return u32(len(b)) + b


def fixed(b: bytes, n: int) -> bytes:
    """Exactly-n bytes (zero is a legal value, e.g. an absent hash)."""
    assert len(b) == n, (len(b), n)
    return b


class Reader:
    """Sequential decoder over one buffer; raises on truncation."""

    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise ValueError("truncated record")
        out = self.buf[self.pos:self.pos + n]
        self.pos += n
        return out

    def u8(self) -> int:
        return self._take(1)[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack(">Q", self._take(8))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def lp_bytes(self) -> bytes:
        return self._take(self.u32())

    def fixed(self, n: int) -> bytes:
        return self._take(n)

    def done(self) -> bool:
        return self.pos == len(self.buf)

    def expect_done(self):
        if not self.done():
            raise ValueError(f"{len(self.buf) - self.pos} trailing bytes")
