"""File-backed validator signing key with anti-double-sign protection.

Reference: `types/priv_validator.go` — monotonic (height, round, step)
guard with last-signature replay (`signBytesHRS` `:206-249`), atomic file
persist on every sign (`:150-167`), pluggable Signer (`:60-63`),
`LoadOrGenPrivValidator` (`:126`).  Signing stays host-side: it is one
signature per consensus step, safety-critical, and never batched.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading

from tendermint_tpu.types.keys import PrivKey, PubKey

# step ordering within a round (reference types/priv_validator.go:22-26)
STEP_NONE = 0
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_VOTE_STEP = {1: STEP_PREVOTE, 2: STEP_PRECOMMIT}


class DoubleSignError(Exception):
    pass


class PrivValidator:
    """Signs votes/proposals, refusing any regression of (H, R, S); for an
    exact (H, R, S) repeat with identical sign-bytes it replays the cached
    signature (crash-recovery idempotence, reference `:228-245`)."""

    def __init__(self, priv_key: PrivKey, file_path: str | None = None):
        self.priv_key = priv_key
        self.pub_key: PubKey = priv_key.pub_key
        self.file_path = file_path
        self.last_height = 0
        self.last_round = 0
        self.last_step = STEP_NONE
        self.last_sign_bytes: bytes = b""
        self.last_signature: bytes = b""
        self._lock = threading.Lock()

    @property
    def address(self) -> bytes:
        return self.pub_key.address

    # -- persistence ----------------------------------------------------
    @classmethod
    def generate(cls, file_path: str | None = None) -> "PrivValidator":
        pv = cls(PrivKey.generate(), file_path)
        if file_path:
            pv.save()
        return pv

    @classmethod
    def load(cls, file_path: str) -> "PrivValidator":
        with open(file_path) as f:
            d = json.load(f)
        pv = cls(PrivKey(bytes.fromhex(d["priv_key"])), file_path)
        pv.last_height = d.get("last_height", 0)
        pv.last_round = d.get("last_round", 0)
        pv.last_step = d.get("last_step", STEP_NONE)
        pv.last_sign_bytes = bytes.fromhex(d.get("last_sign_bytes", ""))
        pv.last_signature = bytes.fromhex(d.get("last_signature", ""))
        return pv

    @classmethod
    def load_or_generate(cls, file_path: str) -> "PrivValidator":
        """Reference `types/priv_validator.go:126` LoadOrGenPrivValidator."""
        if os.path.exists(file_path):
            return cls.load(file_path)
        return cls.generate(file_path)

    def save(self) -> None:
        """Atomic write-then-rename (reference `:150-167`)."""
        if not self.file_path:
            return
        d = {
            "address": self.address.hex(),
            "pub_key": self.pub_key.bytes_.hex(),
            "priv_key": self.priv_key.seed.hex(),
            "last_height": self.last_height,
            "last_round": self.last_round,
            "last_step": self.last_step,
            "last_sign_bytes": self.last_sign_bytes.hex(),
            "last_signature": self.last_signature.hex(),
        }
        dir_ = os.path.dirname(os.path.abspath(self.file_path))
        os.makedirs(dir_, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=dir_, prefix=".privval")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(d, f, indent=2)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.file_path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    # -- signing --------------------------------------------------------
    def _sign_hrs(self, height: int, round_: int, step: int,
                  sign_bytes: bytes) -> bytes:
        """The HRS guard (reference `signBytesHRS` `:206-249`)."""
        with self._lock:
            hrs = (height, round_, step)
            last = (self.last_height, self.last_round, self.last_step)
            if hrs < last:
                raise DoubleSignError(
                    f"sign request {hrs} regresses from {last}")
            if hrs == last:
                if sign_bytes == self.last_sign_bytes:
                    return self.last_signature  # crash-replay idempotence
                raise DoubleSignError(
                    f"conflicting sign-bytes at {hrs} (equivocation)")
            sig = self.priv_key.sign(sign_bytes)
            self.last_height, self.last_round, self.last_step = hrs
            self.last_sign_bytes = sign_bytes
            self.last_signature = sig
            self.save()
            return sig

    def sign_vote(self, chain_id: str, vote) -> bytes:
        """Returns the signature; caller attaches it to the vote."""
        step = _VOTE_STEP[vote.type]
        return self._sign_hrs(vote.height, vote.round, step,
                              vote.sign_bytes(chain_id))

    def sign_proposal(self, chain_id: str, proposal) -> bytes:
        return self._sign_hrs(proposal.height, proposal.round, STEP_PROPOSE,
                              proposal.sign_bytes(chain_id))

    def sign_heartbeat(self, chain_id: str, hb) -> bytes:
        """Heartbeats are not double-sign relevant; plain sign."""
        return self.priv_key.sign(hb.sign_bytes(chain_id))

    def reset(self) -> None:
        """unsafe_reset: clear the HRS state (testing only).  Taken
        under the lock like _sign_hrs — a signer mid-HRS-check must see
        either the old state or the fully-reset one, never a torn mix."""
        with self._lock:
            self.last_height = 0
            self.last_round = 0
            self.last_step = STEP_NONE
            self.last_sign_bytes = b""
            self.last_signature = b""
            self.save()

    def __str__(self):
        return f"PrivValidator[{self.address.hex()[:8]}]"
