"""Genesis document: the chain's initial conditions.

Reference: `types/genesis.go` — `GenesisDoc{genesis_time, chain_id,
validators[{pub_key, amount, name}], app_hash}` as JSON.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field

from tendermint_tpu.types.keys import PubKey
from tendermint_tpu.types.validator import Validator, ValidatorSet


@dataclass
class GenesisValidator:
    pub_key: bytes
    power: int
    name: str = ""


@dataclass
class GenesisDoc:
    chain_id: str
    validators: list[GenesisValidator]
    genesis_time_ns: int = field(
        default_factory=lambda: time.time_ns())
    app_hash: bytes = b""
    app_options: dict = field(default_factory=dict)

    def validator_set(self) -> ValidatorSet:
        return ValidatorSet([
            Validator(PubKey(gv.pub_key), gv.power)
            for gv in self.validators
        ])

    def validate(self) -> None:
        if not self.chain_id:
            raise ValueError("genesis has empty chain_id")
        if not self.validators:
            raise ValueError("genesis has no validators")
        for gv in self.validators:
            if gv.power <= 0:
                raise ValueError(f"validator {gv.name} has power <= 0")

    def to_json(self) -> str:
        return json.dumps({
            "chain_id": self.chain_id,
            "genesis_time_ns": self.genesis_time_ns,
            "app_hash": self.app_hash.hex(),
            "app_options": self.app_options,
            "validators": [
                {"pub_key": gv.pub_key.hex(), "power": gv.power,
                 "name": gv.name}
                for gv in self.validators
            ],
        }, indent=2)

    @classmethod
    def from_json(cls, s: str) -> "GenesisDoc":
        d = json.loads(s)
        doc = cls(
            chain_id=d["chain_id"],
            validators=[
                GenesisValidator(pub_key=bytes.fromhex(v["pub_key"]),
                                 power=int(v["power"]),
                                 name=v.get("name", ""))
                for v in d["validators"]
            ],
            genesis_time_ns=int(d.get("genesis_time_ns", 0)),
            app_hash=bytes.fromhex(d.get("app_hash", "")),
            app_options=d.get("app_options", {}),
        )
        doc.validate()
        return doc

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "GenesisDoc":
        with open(path) as f:
            return cls.from_json(f.read())
