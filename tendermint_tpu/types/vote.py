"""Votes and the weighted 2/3 quorum engine.

Reference: `types/vote.go` (signed vote message) and `types/vote_set.go`
(weighted tally with conflict tracking, peer-claimed majorities, commit
extraction).  The hot path — one ed25519 verify per vote at
`types/vote_set.go:175` — is replaced here by the pluggable crypto backend:
single votes verify scalar host-side, bulk ingestion goes through
`add_votes_batched` which verifies a whole batch in one device call.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tendermint_tpu.types import canonical
from tendermint_tpu.types.codec import Reader, lp_bytes, u32, u64, u8
from tendermint_tpu.utils.chaos import DeviceFault

# re-exported vote types
TYPE_PREVOTE = canonical.TYPE_PREVOTE
TYPE_PRECOMMIT = canonical.TYPE_PRECOMMIT


def _block_id():
    # deferred import: block.py imports Vote for Commit
    from tendermint_tpu.types.block import BlockID
    return BlockID


@dataclass(frozen=True)
class Vote:
    validator_address: bytes
    validator_index: int
    height: int
    round: int
    type: int                      # TYPE_PREVOTE | TYPE_PRECOMMIT
    block_id: "object"             # BlockID; zero = nil vote
    signature: bytes = b""

    def validate_basic(self) -> None:
        """Structural checks on wire-decoded votes: every length is fixed
        so a malformed vote can never shift the sign-bytes layout or a
        batch verifier's lanes."""
        if self.type not in (TYPE_PREVOTE, TYPE_PRECOMMIT):
            raise ValueError(f"bad vote type {self.type}")
        if len(self.validator_address) != 20:
            raise ValueError("validator address must be 20 bytes")
        if self.validator_index < 0 or self.height < 1 or self.round < 0:
            raise ValueError("negative vote index/height/round")
        bid = self.block_id
        if bid.hash and len(bid.hash) != 32:
            raise ValueError("block hash must be 32 bytes or empty")
        if bid.parts.hash and len(bid.parts.hash) != 32:
            raise ValueError("parts hash must be 32 bytes or empty")
        if len(self.signature) != 64:
            raise ValueError("signature must be 64 bytes")

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.sign_bytes(
            chain_id, self.type, self.height, self.round,
            block_hash=self.block_id.hash,
            parts_hash=self.block_id.parts.hash,
            parts_total=self.block_id.parts.total)

    def is_nil(self) -> bool:
        return self.block_id.is_zero()

    def encode(self) -> bytes:
        return (lp_bytes(self.validator_address) + u32(self.validator_index) +
                u64(self.height) + u32(self.round) + u8(self.type) +
                self.block_id.encode() + lp_bytes(self.signature))

    @classmethod
    def decode(cls, r: Reader) -> "Vote":
        BlockID = _block_id()
        return cls(validator_address=r.lp_bytes(), validator_index=r.u32(),
                   height=r.u64(), round=r.u32(), type=r.u8(),
                   block_id=BlockID.decode(r), signature=r.lp_bytes())

    def __str__(self):
        t = {1: "prevote", 2: "precommit"}.get(self.type, f"t{self.type}")
        tgt = "nil" if self.is_nil() else self.block_id.hash.hex()[:12]
        return (f"Vote[{self.validator_index}:"
                f"{self.validator_address.hex()[:8]} {self.height}/"
                f"{self.round} {t} -> {tgt}]")


@dataclass(frozen=True)
class DuplicateVoteEvidence:
    """Proof of equivocation: two different votes for the same (validator,
    height, round, type) (reference `types/vote_set.go:195-211`)."""
    vote_a: Vote
    vote_b: Vote


class ErrVoteConflict(Exception):
    def __init__(self, evidence: DuplicateVoteEvidence):
        super().__init__("conflicting votes (equivocation)")
        self.evidence = evidence


class _BlockVotes:
    """Tally for one BlockID within a VoteSet
    (reference `types/vote_set.go:66-80,417-443`)."""

    __slots__ = ("peer_maj23", "bit_array", "votes", "sum")

    def __init__(self, n: int, peer_maj23: bool):
        self.peer_maj23 = peer_maj23
        self.bit_array = [False] * n
        self.votes: list[Vote | None] = [None] * n
        self.sum = 0

    def add_verified(self, idx: int, vote: Vote, power: int):
        if self.votes[idx] is None:
            self.bit_array[idx] = True
            self.votes[idx] = vote
            self.sum += power


def batch_verify_vote_sigs(chain_id: str, val_set, votes) -> np.ndarray:
    """ONE grouped signature check for votes by members of `val_set` —
    the shared lane assembly under both `VoteSet.add_votes_batched` and
    the consensus receive loop's burst pre-verify.

    Caller guarantees every vote passed `validate_basic` and that
    `val_set.validators[v.validator_index].address` matches — this
    function checks signatures only.  Nil-vote hashes are zero-padded to
    the fixed 32-byte rows `batch_sign_bytes` documents (validate_basic
    pinned all hash lengths, so the padding matches the scalar writer).
    Returns bool[N].

    Lanes ride the unified batch plane at the CONSENSUS class — the
    highest priority: a vote burst preempts any queued light-client or
    CheckTx batch, and the plane may coalesce it with other verify work
    for this validator set already in flight.
    """
    from tendermint_tpu import batchplane
    n = len(votes)
    if n == 0:
        return np.zeros(0, dtype=bool)
    msgs = canonical.batch_sign_bytes(
        chain_id,
        np.asarray([v.type for v in votes], dtype=np.uint8),
        np.asarray([v.height for v in votes], dtype=np.uint64),
        np.asarray([v.round for v in votes], dtype=np.uint32),
        np.frombuffer(b"".join(v.block_id.hash.ljust(32, b"\x00")
                               for v in votes), np.uint8).reshape(n, 32),
        np.frombuffer(b"".join(v.block_id.parts.hash.ljust(32, b"\x00")
                               for v in votes), np.uint8).reshape(n, 32),
        np.asarray([v.block_id.parts.total for v in votes],
                   dtype=np.uint32))
    return batchplane.verify_grouped(
        val_set.set_key(), val_set.pubs_matrix(),
        np.asarray([v.validator_index for v in votes], dtype=np.int32),
        msgs,
        np.frombuffer(b"".join(v.signature for v in votes),
                      np.uint8).reshape(n, 64),
        producer="consensus", klass=batchplane.CLASS_CONSENSUS)


class VoteSet:
    """All votes of one (height, round, type) weighted by validator power
    (reference `types/vote_set.go:46-288`).

    Conflict rule: the first vote per validator counts toward its block's
    sum; a conflicting second vote raises ErrVoteConflict (evidence) but is
    still tracked, and counts for a block once some peer claims a 2/3
    majority for that block via `set_peer_maj23` — exactly the reference's
    byzantine-tolerant accounting.
    """

    def __init__(self, chain_id: str, height: int, round_: int, type_: int,
                 val_set):
        assert height >= 1
        self.chain_id = chain_id
        self.height = height
        self.round = round_
        self.type = type_
        self.val_set = val_set
        n = val_set.size()
        self._votes: list[Vote | None] = [None] * n        # canonical votes
        self._sum = 0                                      # power of _votes
        self._maj23: object | None = None                  # BlockID once hit
        self._votes_by_block: dict[tuple, _BlockVotes] = {}
        self._peer_maj23s: dict[str, object] = {}

    # -- sizing ---------------------------------------------------------
    def size(self) -> int:
        return self.val_set.size()

    # -- ingestion ------------------------------------------------------
    def add_vote(self, vote: Vote, verify: bool = True) -> bool:
        """Returns True if the vote was added, False if duplicate/irrelevant.
        Raises ErrVoteConflict on equivocation, ValueError on bad votes
        (reference `types/vote_set.go:126-194`)."""
        if vote is None:
            raise ValueError("nil vote")
        vote.validate_basic()
        if (vote.height != self.height or vote.round != self.round or
                vote.type != self.type):
            raise ValueError(
                f"vote {vote} does not match VoteSet "
                f"{self.height}/{self.round}/{self.type}")
        idx = vote.validator_index
        if not (0 <= idx < self.size()):
            raise ValueError(f"validator index {idx} out of range")
        val = self.val_set.validators[idx]
        if val.address != vote.validator_address:
            raise ValueError("vote address does not match validator index")
        existing = self._votes[idx]
        if existing is not None and existing.block_id.key() == vote.block_id.key():
            return False  # exact duplicate
        if verify:
            ok = val.pub_key.verify(vote.sign_bytes(self.chain_id),
                                    vote.signature)
            if not ok:
                raise ValueError(f"invalid signature on {vote}")
        return self._add_verified(vote, val.voting_power)

    def add_votes_batched(self, votes: list[Vote]) -> list[bool | Exception]:
        """Bulk ingestion: one batched device verify for all signatures,
        then sequential accounting.  Returns per-vote outcome."""
        if not votes:
            return []
        sel, checkable = [], []
        for i, v in enumerate(votes):
            try:
                v.validate_basic()
            except ValueError:
                continue  # malformed: must not poison the batch lanes
            idx = v.validator_index
            if (v.height == self.height and v.round == self.round and
                    v.type == self.type and idx < self.size() and
                    self.val_set.validators[idx].address ==
                    v.validator_address):
                sel.append(v)
                checkable.append(i)
        ok = np.zeros(len(votes), dtype=bool)
        if checkable:
            try:
                ok[np.array(checkable)] = batch_verify_vote_sigs(
                    self.chain_id, self.val_set, sel)
            except DeviceFault:
                # our crypto ladder is down, not the votes: falling
                # through would label every vote "invalid signature" and
                # punish honest peers for a local fault.  The scalar
                # bigint path cannot device-fault.
                for i, v in zip(checkable, sel):
                    ok[i] = self.val_set.validators[
                        v.validator_index].pub_key.verify(
                            v.sign_bytes(self.chain_id), v.signature)
        out: list[bool | Exception] = []
        for i, v in enumerate(votes):
            if not ok[i]:
                out.append(ValueError(f"invalid vote/signature {v}"))
                continue
            try:
                out.append(self.add_vote(v, verify=False))
            except (ValueError, ErrVoteConflict) as e:
                out.append(e)
        return out

    def _add_verified(self, vote: Vote, power: int) -> bool:
        idx = vote.validator_index
        key = vote.block_id.key()
        existing = self._votes[idx]
        conflict: ErrVoteConflict | None = None
        if existing is None:
            self._votes[idx] = vote
            self._sum += power
        else:
            conflict = ErrVoteConflict(DuplicateVoteEvidence(existing, vote))
            # if the conflicting vote is for the established maj23 block,
            # promote it into the canonical array so make_commit always
            # carries the full +2/3 (reference `types/vote_set.go:219-223`)
            if self._maj23 is not None and self._maj23.key() == key:
                self._votes[idx] = vote
        bv = self._votes_by_block.get(key)
        if bv is None:
            if conflict is not None:
                # conflicting vote for an untracked block: forget it rather
                # than allocate — a byzantine validator signing many distinct
                # hashes must not grow memory (reference vote_set.go:241-244)
                raise conflict
            bv = _BlockVotes(self.size(), peer_maj23=False)
            self._votes_by_block[key] = bv
        elif conflict is not None and not bv.peer_maj23:
            raise conflict
        bv.add_verified(idx, vote, power)
        self._update_maj23(key, vote)
        if conflict is not None:
            raise conflict
        return True

    def _update_maj23(self, key: tuple, vote: Vote):
        bv = self._votes_by_block[key]
        if (self._maj23 is None and
                bv.sum * 3 > self.val_set.total_voting_power() * 2):
            self._maj23 = vote.block_id
            # copy this block's votes over the canonical array so conflicting
            # votes that formed the majority are extractable by make_commit
            # (reference `types/vote_set.go:267-271`)
            for i, v in enumerate(bv.votes):
                if v is not None:
                    self._votes[i] = v

    def set_peer_maj23(self, peer_id: str, block_id) -> None:
        """A peer claims 2/3 for block_id: start counting conflicting votes
        toward it (reference `types/vote_set.go:290-323`)."""
        key = block_id.key()
        prev = self._peer_maj23s.get(peer_id)
        if prev is not None and prev.key() != key:
            raise ValueError(f"peer {peer_id} sent conflicting maj23 claims")
        self._peer_maj23s[peer_id] = block_id
        bv = self._votes_by_block.get(key)
        if bv is None:
            bv = _BlockVotes(self.size(), peer_maj23=True)
            self._votes_by_block[key] = bv
            return
        if bv.peer_maj23:
            return
        bv.peer_maj23 = True
        # recount: canonical votes for this block are already there; pull in
        # any conflicting votes we know of (the reference re-adds from
        # validator indices; we only stored canonical votes, so nothing more
        # to add here — future conflicting votes will be added on arrival)

    # -- queries --------------------------------------------------------
    def get_by_index(self, idx: int) -> Vote | None:
        return self._votes[idx]

    def get_by_address(self, addr: bytes) -> Vote | None:
        idx = self.val_set.index_of(addr)
        return self._votes[idx] if idx >= 0 else None

    def bit_array(self) -> list[bool]:
        return [v is not None for v in self._votes]

    def bit_array_by_block_id(self, block_id) -> list[bool]:
        bv = self._votes_by_block.get(block_id.key())
        return list(bv.bit_array) if bv else [False] * self.size()

    def sum(self) -> int:
        return self._sum

    def has_two_thirds_majority(self) -> bool:
        return self._maj23 is not None

    def two_thirds_majority(self):
        """BlockID (possibly zero = nil) if 2/3 of power agrees, else None
        (reference `types/vote_set.go:254-274`)."""
        return self._maj23

    def has_two_thirds_any(self) -> bool:
        return self._sum * 3 > self.val_set.total_voting_power() * 2

    def has_one_third_any(self) -> bool:
        return self._sum * 3 > self.val_set.total_voting_power()

    def has_all(self) -> bool:
        return self._sum == self.val_set.total_voting_power()

    def make_commit(self):
        """Extract a Commit once 2/3 precommitted a non-nil block
        (reference `types/vote_set.go:455-474`)."""
        from tendermint_tpu.types.block import Commit
        if self.type != TYPE_PRECOMMIT:
            raise ValueError("cannot make commit from non-precommit VoteSet")
        if self._maj23 is None or self._maj23.is_zero():
            raise ValueError("no +2/3 majority for a block")
        key = self._maj23.key()
        precommits: list[Vote | None] = []
        for v in self._votes:
            if v is not None and v.block_id.key() == key:
                precommits.append(v)
            else:
                precommits.append(None)
        return Commit(block_id=self._maj23, precommits=precommits)

    def __str__(self):
        t = {1: "prevote", 2: "precommit"}.get(self.type, f"t{self.type}")
        return (f"VoteSet[{self.height}/{self.round}/{t} "
                f"{self._sum}/{self.val_set.total_voting_power()}]")
