"""ed25519 key types and addresses.

Mirrors the reference's go-crypto surface (`PrivKeyEd25519`,
`PubKey.VerifyBytes`, address = hash of pubkey — reference
`types/priv_validator.go:96-100`, go-crypto).  Addresses here are
sha256(pubkey)[:20] (the reference era used RIPEMD-160; this framework
standardizes on SHA-256 throughout, see SURVEY.md §2.2).

Scalar verification is the LIVE consensus hot path (one ed25519 verify
per arriving vote, reference `types/vote_set.go:175`): it dispatches to
the native OpenSSL-backed verifier when available (~0.13 ms) and only
falls back to the golden bigint implementation (~5 ms) without it.
Signing stays on the bigint path — one signature per consensus step,
cold.  Batch verification goes through `tendermint_tpu.crypto.backend`.
"""

from __future__ import annotations

import functools
import hashlib
import secrets
from dataclasses import dataclass

from tendermint_tpu.crypto import pure_ed25519 as _ed
from tendermint_tpu.crypto import native as _native

ADDRESS_LEN = 20

# Ed25519 verification is a pure function of (pubkey, msg, sig), so its
# result can be memoized soundly.  In-process multi-node rigs (the
# 50-100 validator scenario meshes) hand the SAME wire vote to every
# node: without the memo each of N nodes pays a full scalar verify for
# every vote (N x quadratic work under the GIL); with it the first
# verify settles the question process-wide.  Production single-node
# topology sees only the cost of one dict lookup per repeat.
_VERIFY_MEMO_SIZE = 1 << 16


@functools.lru_cache(maxsize=_VERIFY_MEMO_SIZE)
def _verify_memo(pub: bytes, msg: bytes, sig: bytes) -> bool:
    if _native.AVAILABLE:
        return _native.verify_one(pub, msg, sig)
    return _ed.verify(pub, msg, sig)


def address_from_pubkey(pub: bytes) -> bytes:
    return hashlib.sha256(pub).digest()[:ADDRESS_LEN]


@dataclass(frozen=True)
class PubKey:
    """32-byte ed25519 public key; the gate consensus verifies through
    (reference `types/vote_set.go:175` PubKey.VerifyBytes)."""
    bytes_: bytes

    def __post_init__(self):
        if len(self.bytes_) != 32:
            raise ValueError("pubkey must be 32 bytes")

    @property
    def address(self) -> bytes:
        # cached: address derivation showed up at ~10% of fast-sync apply
        # (one sha256 per validator per proposer-rotation comparison)
        a = self.__dict__.get("_addr")
        if a is None:
            a = self.__dict__["_addr"] = address_from_pubkey(self.bytes_)
        return a

    def verify(self, msg: bytes, sig: bytes) -> bool:
        return _verify_memo(self.bytes_, msg, sig)

    def hex(self) -> str:
        return self.bytes_.hex()


@dataclass(frozen=True)
class PrivKey:
    """32-byte seed; signing is deterministic RFC-8032."""
    seed: bytes

    def __post_init__(self):
        if len(self.seed) != 32:
            raise ValueError("seed must be 32 bytes")

    @classmethod
    def generate(cls) -> "PrivKey":
        return cls(secrets.token_bytes(32))

    @property
    def pub_key(self) -> PubKey:
        return PubKey(_ed.pubkey_from_seed(self.seed))

    def sign(self, msg: bytes) -> bytes:
        if _native.AVAILABLE:
            return _native.sign_one(self.seed, msg)
        return _ed.sign(self.seed, msg)
