"""ed25519 key types and addresses.

Mirrors the reference's go-crypto surface (`PrivKeyEd25519`,
`PubKey.VerifyBytes`, address = hash of pubkey — reference
`types/priv_validator.go:96-100`, go-crypto).  Addresses here are
sha256(pubkey)[:20] (the reference era used RIPEMD-160; this framework
standardizes on SHA-256 throughout, see SURVEY.md §2.2).

Scalar sign/verify run host-side via the golden bigint implementation —
they are cold paths (one signature per consensus step).  Batch verification
goes through `tendermint_tpu.crypto.backend`.
"""

from __future__ import annotations

import hashlib
import secrets
from dataclasses import dataclass

from tendermint_tpu.crypto import pure_ed25519 as _ed

ADDRESS_LEN = 20


def address_from_pubkey(pub: bytes) -> bytes:
    return hashlib.sha256(pub).digest()[:ADDRESS_LEN]


@dataclass(frozen=True)
class PubKey:
    """32-byte ed25519 public key; the gate consensus verifies through
    (reference `types/vote_set.go:175` PubKey.VerifyBytes)."""
    bytes_: bytes

    def __post_init__(self):
        if len(self.bytes_) != 32:
            raise ValueError("pubkey must be 32 bytes")

    @property
    def address(self) -> bytes:
        return address_from_pubkey(self.bytes_)

    def verify(self, msg: bytes, sig: bytes) -> bool:
        return _ed.verify(self.bytes_, msg, sig)

    def hex(self) -> str:
        return self.bytes_.hex()


@dataclass(frozen=True)
class PrivKey:
    """32-byte seed; signing is deterministic RFC-8032."""
    seed: bytes

    def __post_init__(self):
        if len(self.seed) != 32:
            raise ValueError("seed must be 32 bytes")

    @classmethod
    def generate(cls) -> "PrivKey":
        return cls(secrets.token_bytes(32))

    @property
    def pub_key(self) -> PubKey:
        return PubKey(_ed.pubkey_from_seed(self.seed))

    def sign(self, msg: bytes) -> bytes:
        return _ed.sign(self.seed, msg)
