"""SHA-256 simple Merkle tree — host reference implementation.

Tree shape follows the reference exactly (reference `types/tx.go:29-43`,
tmlibs/merkle SimpleTree): leaves are hashed individually, and an n-leaf
tree splits into a floor((n+1)/2) left subtree and the remainder right —
so proofs and roots match between host and the batched device kernel
(`tendermint_tpu.ops.merkle`), which is differential-tested against this.

The reference era used RIPEMD-160; this framework standardizes on SHA-256
(see SURVEY.md §2.2 PartSet note).  Leaf/inner domain separation prevents
second-preimage attacks (a hardening the reference lacks).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

LEAF_PREFIX = b"\x00"
INNER_PREFIX = b"\x01"


def _sha(b: bytes) -> bytes:
    return hashlib.sha256(b).digest()


def leaf_hash(data: bytes) -> bytes:
    return _sha(LEAF_PREFIX + data)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha(INNER_PREFIX + left + right)


def _split(n: int) -> int:
    """Left-subtree size for n leaves: the reference's (n+1)//2 split
    (reference `types/tx.go:33`)."""
    return (n + 1) // 2


def root_from_leaf_hashes(hashes: list[bytes]) -> bytes:
    if not hashes:
        return _sha(b"")
    if len(hashes) == 1:
        return hashes[0]
    k = _split(len(hashes))
    return inner_hash(root_from_leaf_hashes(hashes[:k]),
                      root_from_leaf_hashes(hashes[k:]))


def root(items: list[bytes]) -> bytes:
    """Merkle root over raw byte items."""
    return root_from_leaf_hashes([leaf_hash(i) for i in items])


def root_of_map(kvs: dict[str, bytes]) -> bytes:
    """Deterministic root over a string->bytes map: items are
    lp(key)||lp(value) sorted by key (the reference's SimpleHashFromMap,
    used for `Header.Hash`, reference `types/block.go:178-193`)."""
    items = []
    for k in sorted(kvs):
        kb = k.encode()
        v = kvs[k]
        items.append(len(kb).to_bytes(4, "big") + kb +
                     len(v).to_bytes(4, "big") + v)
    return root(items)


@dataclass(frozen=True)
class Proof:
    """Inclusion proof: sibling hashes from leaf to root.

    `aunts[i]` is the sibling at depth i counting from the leaf; `index` /
    `total` fix the path shape (reference `types/part_set.go:188-214`).
    """
    total: int
    index: int
    leaf: bytes          # leaf *hash*
    aunts: tuple[bytes, ...]

    def compute_root(self) -> bytes:
        return _compute_from_aunts(self.index, self.total, self.leaf,
                                   list(self.aunts))

    def verify(self, expected_root: bytes) -> bool:
        if not (0 <= self.index < self.total):
            return False
        try:
            return self.compute_root() == expected_root
        except (ValueError, IndexError):
            # IndexError: proof carries fewer aunts than the path depth
            return False


def _compute_from_aunts(index: int, total: int, leaf: bytes,
                        aunts: list[bytes]) -> bytes:
    assert total >= 1
    if total == 1:
        if aunts:
            raise ValueError("unexpected aunts for single leaf")
        return leaf
    k = _split(total)
    if index < k:
        left = _compute_from_aunts(index, k, leaf, aunts[:-1])
        return inner_hash(left, aunts[-1])
    right = _compute_from_aunts(index - k, total - k, leaf, aunts[:-1])
    return inner_hash(aunts[-1], right)


def proofs(items: list[bytes]) -> tuple[bytes, list[Proof]]:
    """Root plus one inclusion proof per item."""
    return proofs_from_leaf_hashes([leaf_hash(i) for i in items])


def proofs_from_leaf_hashes(hashes: list[bytes]) -> tuple[bytes, list[Proof]]:
    """Root + proofs from precomputed leaf hashes — the seam that lets the
    bulk leaf hashing run on the device (`ops.merkle.leaf_hashes`) while
    the irregular tree/proof assembly stays host-side."""
    n = len(hashes)
    if n == 0:
        return root([]), []
    trails: list[list[bytes]] = [[] for _ in range(n)]

    def build(lo: int, hi: int) -> bytes:
        if hi - lo == 1:
            return hashes[lo]
        k = _split(hi - lo)
        left = build(lo, lo + k)
        right = build(lo + k, hi)
        for i in range(lo, lo + k):
            trails[i].append(right)
        for i in range(lo + k, hi):
            trails[i].append(left)
        return inner_hash(left, right)

    rt = build(0, n)
    return rt, [Proof(n, i, hashes[i], tuple(trails[i])) for i in range(n)]
