"""Domain types: blocks, votes, validators, and the crypto-plane contracts.

The layer every other layer compiles against (reference `types/`,
SURVEY.md §2.2).
"""

from tendermint_tpu.types.block import (Block, BlockID, Commit, CompactCommit, EMPTY_COMMIT,
                                        Header, ZERO_BLOCK_ID)
from tendermint_tpu.types.canonical import (SIGN_BYTES_LEN, TYPE_HEARTBEAT,
                                            TYPE_PRECOMMIT, TYPE_PREVOTE,
                                            TYPE_PROPOSAL)
from tendermint_tpu.types.genesis import GenesisDoc, GenesisValidator
from tendermint_tpu.types.keys import PrivKey, PubKey, address_from_pubkey
from tendermint_tpu.types.part_set import (PART_SIZE, Part, PartSet,
                                           PartSetHeader, ZERO_PSH)
from tendermint_tpu.types.priv_validator import DoubleSignError, PrivValidator
from tendermint_tpu.types.proposal import Heartbeat, Proposal
from tendermint_tpu.types.tx import Tx, TxProof, txs_hash, txs_proof
from tendermint_tpu.types.validator import Validator, ValidatorSet
from tendermint_tpu.types.vote import (DuplicateVoteEvidence, ErrVoteConflict,
                                       Vote, VoteSet)

__all__ = [
    "Block", "BlockID", "Commit", "CompactCommit", "EMPTY_COMMIT", "Header",
    "ZERO_BLOCK_ID",
    "SIGN_BYTES_LEN", "TYPE_HEARTBEAT", "TYPE_PRECOMMIT", "TYPE_PREVOTE",
    "TYPE_PROPOSAL", "GenesisDoc", "GenesisValidator", "PrivKey", "PubKey",
    "address_from_pubkey", "PART_SIZE", "Part", "PartSet", "PartSetHeader",
    "ZERO_PSH", "DoubleSignError", "PrivValidator", "Heartbeat", "Proposal",
    "Tx", "TxProof", "txs_hash", "txs_proof", "Validator", "ValidatorSet",
    "DuplicateVoteEvidence", "ErrVoteConflict", "Vote", "VoteSet",
]
