"""Fixed-layout sign-bytes — the contract between consensus and the TPU.

The reference signs reflection-generated canonical JSON (reference
`types/canonical_json.go:44-58`, `types/vote.go:60-66`).  This framework
instead defines a *fixed 128-byte* binary layout so that a batch of N votes
is an `uint8[N, 128]` array assembled with pure memory moves (numpy
host-side) — no per-item serialization — and the device kernel hashes and
verifies thousands in lockstep (`tendermint_tpu.ops.ed25519`).

Layout (big-endian, zero-padded to 128 bytes):

    off  len  field
    0    4    magic  b"TMS1"  (framework sign-bytes, version 1)
    4    1    msg type        (1=prevote 2=precommit 3=proposal 4=heartbeat)
    5    3    zero padding
    8    32   sha256(chain_id)
    40   8    height   u64
    48   4    round    u32
    52   32   block hash       (zeros = nil vote)
    84   32   part-set hash    (zeros = nil)
    116  4    part-set total   u32
    120  4    pol_round + 1    u32 (proposals; 0 = no POL)   [votes: 0]
    124  4    zero padding

Every field is fixed-width; chain IDs of any length hash to 32 bytes.  A
vote's sign-bytes are therefore reconstructable on device from the tuple
(chain_hash, height, round, type, block_id) — the property SURVEY.md §7
calls out as hard requirement #2.
"""

from __future__ import annotations

import hashlib

import numpy as np

SIGN_BYTES_LEN = 128
MAGIC = b"TMS1"

TYPE_PREVOTE = 1
TYPE_PRECOMMIT = 2
TYPE_PROPOSAL = 3
TYPE_HEARTBEAT = 4

_OFF_TYPE = 4
_OFF_CHAIN = 8
_OFF_HEIGHT = 40
_OFF_ROUND = 48
_OFF_BLOCKHASH = 52
_OFF_PARTSHASH = 84
_OFF_PARTSTOTAL = 116
_OFF_POLROUND = 120


def chain_hash(chain_id: str) -> bytes:
    return hashlib.sha256(chain_id.encode()).digest()


def sign_bytes(chain_id: str, msg_type: int, height: int, round_: int,
               block_hash: bytes = b"", parts_hash: bytes = b"",
               parts_total: int = 0, pol_round: int = -1) -> bytes:
    """One record, host path (device batch path: `batch_sign_bytes`)."""
    # hashes are exactly 32 bytes or absent — a wire-decoded value of any
    # other length must never silently shift the fixed layout
    if block_hash and len(block_hash) != 32:
        raise ValueError(f"block_hash must be 32 bytes, got {len(block_hash)}")
    if parts_hash and len(parts_hash) != 32:
        raise ValueError(f"parts_hash must be 32 bytes, got {len(parts_hash)}")
    buf = bytearray(SIGN_BYTES_LEN)
    buf[0:4] = MAGIC
    buf[_OFF_TYPE] = msg_type
    buf[_OFF_CHAIN:_OFF_CHAIN + 32] = chain_hash(chain_id)
    buf[_OFF_HEIGHT:_OFF_HEIGHT + 8] = height.to_bytes(8, "big")
    buf[_OFF_ROUND:_OFF_ROUND + 4] = round_.to_bytes(4, "big")
    if block_hash:
        buf[_OFF_BLOCKHASH:_OFF_BLOCKHASH + 32] = block_hash
    if parts_hash:
        buf[_OFF_PARTSHASH:_OFF_PARTSHASH + 32] = parts_hash
    buf[_OFF_PARTSTOTAL:_OFF_PARTSTOTAL + 4] = parts_total.to_bytes(4, "big")
    buf[_OFF_POLROUND:_OFF_POLROUND + 4] = (pol_round + 1).to_bytes(4, "big")
    return bytes(buf)


def batch_sign_bytes(chain_id: str, msg_types: np.ndarray,
                     heights: np.ndarray, rounds: np.ndarray,
                     block_hashes: np.ndarray,
                     parts_hashes: np.ndarray,
                     parts_totals: np.ndarray) -> np.ndarray:
    """Vectorized assembly: N votes -> uint8[N, 128] with no Python loop.

    block_hashes/parts_hashes are uint8[N, 32] (zero rows = nil).
    """
    n = len(heights)
    buf = np.zeros((n, SIGN_BYTES_LEN), dtype=np.uint8)
    buf[:, 0:4] = np.frombuffer(MAGIC, dtype=np.uint8)
    buf[:, _OFF_TYPE] = msg_types.astype(np.uint8)
    buf[:, _OFF_CHAIN:_OFF_CHAIN + 32] = np.frombuffer(chain_hash(chain_id),
                                                       dtype=np.uint8)
    h = heights.astype(">u8").view(np.uint8).reshape(n, 8)
    buf[:, _OFF_HEIGHT:_OFF_HEIGHT + 8] = h
    r = rounds.astype(">u4").view(np.uint8).reshape(n, 4)
    buf[:, _OFF_ROUND:_OFF_ROUND + 4] = r
    buf[:, _OFF_BLOCKHASH:_OFF_BLOCKHASH + 32] = block_hashes
    buf[:, _OFF_PARTSHASH:_OFF_PARTSHASH + 32] = parts_hashes
    t = parts_totals.astype(">u4").view(np.uint8).reshape(n, 4)
    buf[:, _OFF_PARTSTOTAL:_OFF_PARTSTOTAL + 4] = t
    # votes carry pol_round = -1 -> stored 0 == already zeroed
    return buf
