"""Proposal and heartbeat messages.

Reference: `types/proposal.go` (signed block proposal with POL round for
lock changes) and `types/heartbeat.go` (proposer liveness signal).
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.types import canonical
from tendermint_tpu.types.codec import Reader, i64, lp_bytes, u32, u64
from tendermint_tpu.types.part_set import PartSetHeader


@dataclass(frozen=True)
class Proposal:
    height: int
    round: int
    block_parts_header: PartSetHeader
    pol_round: int = -1            # -1: no proof-of-lock
    pol_block_id: "object" = None  # BlockID | None
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        pol = self.pol_block_id
        return canonical.sign_bytes(
            chain_id, canonical.TYPE_PROPOSAL, self.height, self.round,
            block_hash=(pol.hash if pol is not None else b""),
            parts_hash=self.block_parts_header.hash,
            parts_total=self.block_parts_header.total,
            pol_round=self.pol_round)

    def encode(self) -> bytes:
        from tendermint_tpu.types.block import ZERO_BLOCK_ID
        pol = self.pol_block_id if self.pol_block_id is not None else ZERO_BLOCK_ID
        return (u64(self.height) + u32(self.round) +
                self.block_parts_header.encode() + i64(self.pol_round) +
                pol.encode() + lp_bytes(self.signature))

    @classmethod
    def decode(cls, r: Reader) -> "Proposal":
        from tendermint_tpu.types.block import BlockID
        height, round_ = r.u64(), r.u32()
        parts = PartSetHeader.decode(r)
        pol_round = r.i64()
        pol_block_id = BlockID.decode(r)
        sig = r.lp_bytes()
        if pol_block_id.is_zero():
            pol_block_id = None
        return cls(height, round_, parts, pol_round, pol_block_id, sig)

    def __str__(self):
        return (f"Proposal[{self.height}/{self.round} "
                f"parts {self.block_parts_header} pol {self.pol_round}]")


@dataclass(frozen=True)
class Heartbeat:
    validator_address: bytes
    validator_index: int
    height: int
    round: int
    sequence: int
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        # reuse the fixed frame: sequence rides in the parts_total slot
        if len(self.validator_address) > 32:
            raise ValueError("validator address too long")
        return canonical.sign_bytes(
            chain_id, canonical.TYPE_HEARTBEAT, self.height, self.round,
            block_hash=self.validator_address.ljust(32, b"\x00"),
            parts_total=self.sequence)

    def encode(self) -> bytes:
        # index -1 = sender is not a validator (reference Heartbeat
        # carries ValidatorIndex -1 for observers); shift like the other
        # minus-one-able wire fields
        return (lp_bytes(self.validator_address) +
                u32(self.validator_index + 1) +
                u64(self.height) + u32(self.round) + u64(self.sequence) +
                lp_bytes(self.signature))

    @classmethod
    def decode(cls, r: Reader) -> "Heartbeat":
        return cls(validator_address=r.lp_bytes(),
                   validator_index=r.u32() - 1,
                   height=r.u64(), round=r.u32(), sequence=r.u64(),
                   signature=r.lp_bytes())
