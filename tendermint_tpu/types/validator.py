"""Validators, the validator set, and batched commit verification.

Reference: `types/validator.go`, `types/validator_set.go` — address-sorted
validator array with voting power, accumulated-priority proposer rotation
(`:52-69`), Merkle hash over validators (`:140-149`), and `VerifyCommit`
(`:220-264`) — THE fast-sync hot loop (reference
`blockchain/reactor.go:230-231`): ~N ed25519 verifies per block, done here
as one crypto-backend batch instead of a scalar loop.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tendermint_tpu.types import canonical, merkle
from tendermint_tpu.types.codec import Reader, i64, lp_bytes, u32
from tendermint_tpu.types.keys import PubKey


class CommitSignatureError(ValueError):
    """A commit carries an invalid signature.  In fast-sync the commit for
    height h travels in block h+1's LastCommit, so the *successor's*
    deliverer is at fault."""

    def __init__(self, height: int, lane: int):
        super().__init__(
            f"invalid commit signature at height {height} (lane {lane})")
        self.height = height
        self.lane = lane


class CommitPowerError(ValueError):
    """A commit's tallied power for the expected block is below +2/3.

    `foreign_votes` disambiguates the two causes so fast-sync blames the
    right deliverer: True = verified votes endorse a DIFFERENT non-nil
    block, i.e. the block at `height` itself is not what the network
    committed (its deliverer lied); False = every vote endorses our
    block but too few are present — the commit (carried by the SUCCESSOR
    block's LastCommit) was pruned, so height+1's deliverer lied."""

    def __init__(self, height: int, tallied: int, total: int,
                 foreign_votes: bool = True):
        super().__init__(
            f"insufficient voting power at height {height}: "
            f"{tallied}/{total}"
            f"{' (votes for another block)' if foreign_votes else ''}")
        self.height = height
        self.foreign_votes = foreign_votes


class CommitFormatError(ValueError):
    """A commit is structurally unusable as the +2/3 proof for `height`:
    wrong height (a STALE finality proof replayed from an older block),
    wrong size, or malformed votes.  Like a pruned commit it rides in the
    successor block's LastCommit, so height+1's deliverer is at fault —
    without this mapping a replayed stale commit would raise a bare
    ValueError that fast-sync can only log, stalling the pool forever
    instead of evicting the liar."""

    def __init__(self, height: int, detail: str):
        super().__init__(
            f"unusable commit for height {height}: {detail}")
        self.height = height


@dataclass
class Validator:
    pub_key: PubKey
    voting_power: int
    accum: int = 0

    @property
    def address(self) -> bytes:
        return self.pub_key.address

    @property
    def sort_key(self) -> bytes:
        """Cached `_neg_addr(address)` — the proposer-rotation tie-break
        runs V comparisons per block, so this is per-block hot."""
        k = self.__dict__.get("_sort_key")
        if k is None:
            k = self.__dict__["_sort_key"] = _neg_addr(self.address)
        return k

    def copy(self) -> "Validator":
        v = Validator(self.pub_key, self.voting_power, self.accum)
        if "_sort_key" in self.__dict__:
            v.__dict__["_sort_key"] = self.__dict__["_sort_key"]
        return v

    def encode(self) -> bytes:
        return (lp_bytes(self.pub_key.bytes_) + i64(self.voting_power) +
                i64(self.accum))

    @classmethod
    def decode(cls, r: Reader) -> "Validator":
        return cls(pub_key=PubKey(r.lp_bytes()), voting_power=r.i64(),
                   accum=r.i64())

    def hash_bytes(self) -> bytes:
        """The bytes committed into the validators hash."""
        return lp_bytes(self.pub_key.bytes_) + i64(self.voting_power)

    def __str__(self):
        return f"Val[{self.address.hex()[:8]} pow {self.voting_power}]"


class ValidatorSet:
    """Address-sorted validators with proposer rotation
    (reference `types/validator_set.go:20-69`)."""

    def __init__(self, validators: list[Validator]):
        vals = sorted((v.copy() for v in validators),
                      key=lambda v: v.address)
        addrs = [v.address for v in vals]
        if len(set(addrs)) != len(addrs):
            raise ValueError("duplicate validator address")
        self.validators = vals
        self._total = sum(v.voting_power for v in vals)
        self._by_addr = {v.address: i for i, v in enumerate(vals)}
        self._proposer: Validator | None = None
        # accumulated priorities live in THIS ARRAY, not on the Validator
        # objects (v.accum is a construction-time input / decode field
        # only): rotation happens every block and every round, and
        # array-residency makes increment_accum pure numpy and copy() an
        # array copy instead of V object allocations — the two were ~18%
        # of the fast-sync apply stage at V=100
        self._accums = np.fromiter((v.accum for v in vals), np.int64,
                                   len(vals))
        if vals:
            self.increment_accum(1)

    def accum_of(self, i: int) -> int:
        """Accumulated priority of validators[i] (authoritative — the
        objects' .accum fields are not updated by rotation)."""
        return int(self._accums[i])

    # -- basics ---------------------------------------------------------
    def size(self) -> int:
        return len(self.validators)

    def total_voting_power(self) -> int:
        return self._total

    def index_of(self, address: bytes) -> int:
        return self._by_addr.get(address, -1)

    def get_by_address(self, address: bytes) -> Validator | None:
        i = self.index_of(address)
        return self.validators[i] if i >= 0 else None

    def has_address(self, address: bytes) -> bool:
        return address in self._by_addr

    def copy(self) -> "ValidatorSet":
        """O(1)-ish copy: Validator objects are immutable after set
        construction (rotation state lives in `_accums`; `apply_updates`
        replaces objects copy-on-write), so copies SHARE them — only the
        accum array, the list, and the index dict are duplicated."""
        new = ValidatorSet.__new__(ValidatorSet)
        new.validators = list(self.validators)
        new._total = self._total
        new._by_addr = dict(self._by_addr)
        new._proposer = self._proposer
        new._accums = self._accums.copy()
        # membership-derived caches survive a copy (invalidated only by
        # apply_updates); the hash also survives accum rotation because
        # hash_bytes excludes accum
        for attr in ("_set_key", "_pubs_mat", "_hash", "_powers", "_enc"):
            if attr in self.__dict__:
                new.__dict__[attr] = self.__dict__[attr]
        return new

    # -- proposer rotation ---------------------------------------------
    def _powers_arr(self) -> np.ndarray:
        p = self.__dict__.get("_powers")
        if p is None:
            p = self.__dict__["_powers"] = np.array(
                [v.voting_power for v in self.validators], dtype=np.int64)
        return p

    def increment_accum(self, times: int) -> None:
        """Accumulated-priority rotation (reference
        `types/validator_set.go:52-69`): each step every validator gains
        accum += power; the max-accum validator (ties: lowest address)
        becomes proposer and pays total power.

        Vectorized: the per-step Python max over (accum, sort_key)
        tuples was ~0.2 ms/block at V=100 — a leading slice of the
        fast-sync apply stage (VERDICT r4 #5).  numpy argmax decides;
        the byte-string tie-break only runs on actual accum ties
        (equal-power sets at specific heights)."""
        vals = self.validators
        powers = self._powers_arr()
        accums = self._accums
        for _ in range(times):
            accums += powers
            i = int(np.argmax(accums))
            ties = np.flatnonzero(accums == accums[i])
            if len(ties) > 1:
                i = max((int(t) for t in ties),
                        key=lambda t: vals[t].sort_key)
            accums[i] -= self._total
            self._proposer = vals[i]
        self.__dict__.pop("_enc", None)    # accum is part of encode()

    @property
    def proposer(self) -> Validator:
        assert self._proposer is not None
        return self._proposer

    # -- hashing / codec ------------------------------------------------
    def hash(self) -> bytes:
        """Merkle root over validators (reference
        `types/validator_set.go:140-149`).  Cached: recomputing this tree
        per block was ~1/3 of fast-sync apply; accum rotation does not
        change it (hash_bytes excludes accum), only apply_updates does."""
        h = self.__dict__.get("_hash")
        if h is None:
            h = self.__dict__["_hash"] = merkle.root(
                [v.hash_bytes() for v in self.validators])
        return h

    def set_key(self) -> bytes:
        """Stable identity for crypto-backend table caching: a digest of
        the MEMBER PUBKEYS only — comb tables depend on keys, not powers,
        so a power-only EndBlock diff must not force a table rebuild."""
        k = getattr(self, "_set_key", None)
        if k is None:
            import hashlib
            k = self._set_key = hashlib.sha256(
                self.pubs_matrix().tobytes()).digest()
        return k

    def pubs_matrix(self) -> np.ndarray:
        """uint8[V, 32] of member pubkeys in validator order — the
        fixed key set handed to Backend.verify_grouped."""
        m = getattr(self, "_pubs_mat", None)
        if m is None:
            m = np.frombuffer(
                b"".join(v.pub_key.bytes_ for v in self.validators),
                np.uint8).reshape(len(self.validators), 32)
            self._pubs_mat = m
        return m

    def encode(self) -> bytes:
        """Vectorized assembly: the state layer persists BOTH valsets on
        every committed block, so a per-validator Python loop (~200 calls
        at V=100) is real per-block cost in fast-sync replay.  Entries are
        fixed 52-byte rows (u32 len=32 || pub32 || i64 power || i64 accum)
        built in one numpy buffer.  Cached until accum/membership changes
        (state persistence encodes the same set up to three times per
        committed block: state.validators, the height-keyed history row,
        and next block's last_validators)."""
        e = self.__dict__.get("_enc")
        if e is not None:
            return e
        n = len(self.validators)
        rows = np.zeros((n, 52), dtype=np.uint8)
        rows[:, 0:4] = np.frombuffer(u32(32) * n,
                                     np.uint8).reshape(n, 4)
        rows[:, 4:36] = self.pubs_matrix()
        rows[:, 36:44] = np.asarray(
            [v.voting_power for v in self.validators],
            dtype=">i8").view(np.uint8).reshape(n, 8)
        rows[:, 44:52] = self._accums.astype(
            ">i8").view(np.uint8).reshape(n, 8)
        prop = self.index_of(self._proposer.address) if self._proposer else -1
        e = self.__dict__["_enc"] = u32(n) + rows.tobytes() + i64(prop)
        return e

    @classmethod
    def decode(cls, r: Reader) -> "ValidatorSet":
        n = r.u32()
        vals = [Validator.decode(r) for _ in range(n)]
        prop = r.i64()
        vs = cls.__new__(cls)
        vs.validators = vals   # already sorted when encoded
        vs._total = sum(v.voting_power for v in vals)
        vs._by_addr = {v.address: i for i, v in enumerate(vals)}
        vs._proposer = vals[prop] if 0 <= prop < len(vals) else None
        vs._accums = np.fromiter((v.accum for v in vals), np.int64,
                                 len(vals))
        return vs

    # -- membership updates (ABCI EndBlock diffs) ------------------------
    def apply_updates(self, changes: list[tuple[bytes, int]]) -> None:
        """(pubkey, power) diffs; power 0 removes (reference
        `state/execution.go:117-156` updateValidators).

        COPY-ON-WRITE on the touched validators: objects are shared
        between set copies (see `copy`), so a power change replaces the
        object instead of mutating it.  Surviving validators keep their
        accumulated priority (from this set's array); new entrants start
        at 0 — the reference's semantics."""
        accums = {v.address: int(a)
                  for v, a in zip(self.validators, self._accums)}
        vals = {v.address: v for v in self.validators}
        for pub, power in changes:
            pk = PubKey(pub)
            addr = pk.address
            if power < 0:
                raise ValueError("negative voting power")
            if power == 0:
                if addr not in vals:
                    raise ValueError("removing unknown validator")
                del vals[addr]
            else:
                vals[addr] = Validator(pk, power)
                accums.setdefault(addr, 0)   # survivors keep theirs
        self.validators = sorted(vals.values(), key=lambda v: v.address)
        self._accums = np.fromiter(
            (accums[v.address] for v in self.validators), np.int64,
            len(self.validators))
        self._total = sum(v.voting_power for v in self.validators)
        self._by_addr = {v.address: i for i, v in enumerate(self.validators)}
        self._set_key = None     # membership/power changed: invalidate
        self._pubs_mat = None    # the grouped-verify identity + key matrix
        self.__dict__.pop("_hash", None)
        self.__dict__.pop("_enc", None)
        self.__dict__.pop("_powers", None)
        if (self._proposer is not None and
                self._proposer.address not in self._by_addr):
            self._proposer = None
        elif self._proposer is not None:
            # re-point at the (possibly replaced copy-on-write) object in
            # self.validators — a re-powered proposer must not linger as
            # the stale pre-update object
            self._proposer = self.validators[
                self._by_addr[self._proposer.address]]
        if self._proposer is None and self.validators:
            self.increment_accum(1)

    # -- commit verification (the TPU hot path) --------------------------
    def commit_verify_arrays(self, chain_id: str, block_id, height: int,
                             commit) -> tuple:
        """Flatten a commit into verify arrays so callers can batch many
        commits into one device call.

        Returns (pubs[N,32], msgs[N,128], sigs[N,64], powers[N], idxs[N])
        covering EVERY non-nil precommit at (height, commit.round) — all
        signatures must verify, matching the reference's VerifyCommit which
        rejects a commit carrying any invalid signature — with powers[i] = 0
        for precommits voting a different block (verified but not tallied)
        and idxs[i] the signer's validator index (grouped-verify lane map).
        A structural error in any precommit raises ValueError.

        Derived from `commit_verify_lanes` — the per-vote validation
        lives in exactly one place — by expanding the message templates.
        """
        templates, tmpl_idx, sigs, powers, idxs, _ = \
            self.commit_verify_lanes(chain_id, block_id, height, commit)
        return (self.pubs_matrix()[idxs], templates[tmpl_idx], sigs,
                powers, idxs)

    def commit_verify_lanes(self, chain_id: str, block_id, height: int,
                            commit) -> tuple:
        """Template form of `commit_verify_arrays`: vote sign-bytes do
        not include the signer, so lanes voting the same block share ONE
        128-byte message — a commit compresses to ~1 template plus
        per-lane (sig, validator index, template index).  Device backends
        ship only the indices and assemble messages on device.

        Returns (templates[T,128], tmpl_idx[N], sigs[N,64], powers[N],
        idxs[N], foreign_power int) — foreign_power totals the voting
        power of lanes endorsing a different NON-NIL block (the blame
        disambiguator for CommitPowerError: a single Byzantine stray
        vote must not redirect fast-sync blame when the real defect is a
        pruned LastCommit).
        """
        from tendermint_tpu.types.block import CompactCommit
        if isinstance(commit, CompactCommit):
            return self._compact_commit_lanes(chain_id, block_id, height,
                                              commit)
        if self.size() != commit.size():
            raise ValueError(
                f"commit size {commit.size()} != valset size {self.size()}")
        if commit.height() != height:
            raise ValueError(f"commit height {commit.height()} != {height}")
        round_ = commit.round()
        bid_key = block_id.key()
        tmpl_of: dict[tuple, int] = {}
        templates: list[bytes] = []
        tmpl_idx, sigs, powers, idxs = [], [], [], []
        foreign_power = 0
        for idx, v in enumerate(commit.precommits):
            if v is None:
                continue
            try:
                v.validate_basic()   # fixed lengths: no lane misalignment
            except ValueError as e:
                raise ValueError(f"commit vote {idx}: {e}") from None
            if v.type != canonical.TYPE_PRECOMMIT:
                raise ValueError(f"commit vote {idx} not a precommit")
            if v.height != height or v.round != round_:
                raise ValueError(f"commit vote {idx} wrong height/round")
            if v.validator_index != idx:
                raise ValueError(
                    f"commit vote index {v.validator_index}!={idx}")
            val = self.validators[idx]
            if val.address != v.validator_address:
                raise ValueError(f"commit vote {idx} address mismatch")
            vkey = v.block_id.key()
            ti = tmpl_of.get(vkey)
            if ti is None:
                ti = tmpl_of[vkey] = len(templates)
                templates.append(v.sign_bytes(chain_id))
            tmpl_idx.append(ti)
            sigs.append(v.signature)
            if vkey == bid_key:
                powers.append(val.voting_power)
            else:
                powers.append(0)
                if not v.block_id.is_zero():
                    foreign_power += val.voting_power
            idxs.append(idx)
        n = len(idxs)
        return (
            np.frombuffer(b"".join(templates), np.uint8).reshape(
                len(templates), canonical.SIGN_BYTES_LEN),
            np.asarray(tmpl_idx, dtype=np.int32),
            np.frombuffer(b"".join(sigs), np.uint8).reshape(n, 64),
            np.asarray(powers, dtype=np.int64),
            np.asarray(idxs, dtype=np.int32),
            foreign_power,
        )

    def _compact_commit_lanes(self, chain_id: str, block_id, height: int,
                              cc) -> tuple:
        """`commit_verify_lanes` for the array-native `CompactCommit`:
        the per-vote Python loop collapses to numpy — every present lane
        shares the commit's (height, round, block_id), so there is ONE
        template, the sigs matrix slices directly into lanes, and powers
        come from the cached power array.  Same return contract and the
        same strictness (shape checks replace per-vote field checks —
        fixed-width arrays cannot misalign lanes)."""
        cc.validate_basic()
        if self.size() != cc.size():
            raise ValueError(
                f"commit size {cc.size()} != valset size {self.size()}")
        if cc.height() != height:
            raise ValueError(f"commit height {cc.height()} != {height}")
        tmpl = canonical.sign_bytes(
            chain_id, canonical.TYPE_PRECOMMIT, height, cc.round(),
            block_hash=cc.block_id.hash,
            parts_hash=cc.block_id.parts.hash,
            parts_total=cc.block_id.parts.total)
        idxs = np.flatnonzero(cc.present).astype(np.int32)
        sigs = np.ascontiguousarray(cc.sigs[idxs])
        n = len(idxs)
        if cc.block_id.key() == block_id.key():
            powers = self._powers_arr()[idxs]
            foreign_power = 0
        else:   # the whole commit endorses another (or nil) block
            powers = np.zeros(n, dtype=np.int64)
            foreign_power = (0 if cc.block_id.is_zero()
                             else int(self._powers_arr()[idxs].sum()))
        return (np.frombuffer(tmpl, np.uint8).reshape(
                    1, canonical.SIGN_BYTES_LEN),
                np.zeros(n, dtype=np.int32), sigs,
                powers.astype(np.int64), idxs, foreign_power)

    def verify_commit(self, chain_id: str, block_id, height: int,
                      commit, producer: str = "fastsync",
                      klass: str | None = None) -> None:
        """Raise unless +2/3 of this set signed block_id at height
        (reference `types/validator_set.go:220-264`); signatures checked in
        one batch-plane submission against this set's cached comb tables
        (`producer`/`klass` name the workload for scheduling + metrics)."""
        from tendermint_tpu import batchplane
        templates, tmpl_idx, sigs, powers, idxs, foreign_power = \
            self.commit_verify_lanes(chain_id, block_id, height, commit)
        ok = batchplane.verify_grouped_templated(
            self.set_key(), self.pubs_matrix(), idxs, tmpl_idx,
            templates, sigs, producer=producer,
            klass=klass or batchplane.CLASS_FASTSYNC)
        if not ok.all():
            raise CommitSignatureError(height, int(np.argmin(ok)))
        tallied = int(powers.sum())
        if not tallied * 3 > self._total * 2:
            raise CommitPowerError(
                height, tallied, self._total,
                _foreign_explains_shortfall(tallied, foreign_power,
                                            self._total))

    def __str__(self):
        return (f"ValidatorSet[{self.size()} vals, "
                f"power {self._total}]")


def merge_commit_lanes(arrays: list[tuple]) -> tuple:
    """Concatenate per-commit `commit_verify_lanes` tuples into one
    device batch, rebasing each commit's template indices onto the
    combined template block.  Returns (templates, tmpl_idx, sigs, idxs).
    """
    t_off, offs = 0, []
    for a in arrays:
        offs.append(t_off)
        t_off += len(a[0])
    return (np.concatenate([a[0] for a in arrays]),
            np.concatenate([a[1] + o for a, o in zip(arrays, offs)]),
            np.concatenate([a[2] for a in arrays]),
            np.concatenate([a[4] for a in arrays]))


def _window_fast_eligible(val_set: ValidatorSet, items: list[tuple]) -> bool:
    """True when every commit in the window satisfies, by inspection, all
    preconditions the per-block `_compact_commit_lanes` checks — so the
    vectorized pass below cannot diverge from the loop it replaces.  Any
    violation (or any object-form commit) routes to the per-block path,
    which raises the canonical error with the canonical message."""
    from tendermint_tpu.types.block import CompactCommit
    v = val_set.size()
    return v > 0 and all(
        isinstance(c, CompactCommit)
        and len(c.present) == v
        and c.height_ == h
        and c.sigs.shape == (v, 64)
        and len(c.block_id.hash) == 32
        and len(c.block_id.parts.hash) == 32
        for _bid, h, c in items)


def window_commit_lanes(val_set: ValidatorSet, chain_id: str,
                        items: list[tuple]) -> tuple:
    """Window-level lane builder: the vectorized fusion of per-block
    `commit_verify_lanes` + `merge_commit_lanes` over a whole fast-sync
    window (`items` = [(block_id, height, commit)]).

    The per-block loop is the replay pipeline's scalar tail: 625 rounds
    of sign-bytes assembly, flatnonzero, sig-slice copies, and a 625-way
    concatenate, all holding the GIL inside the prep stage.  When every
    commit is an array-native `CompactCommit` (the form fast-sync
    stores), the whole window collapses to one `batch_sign_bytes` call,
    one boolean-matrix nonzero, and one fancy-indexed sig gather —
    byte-identical to the loop (property-tested), a couple of numpy
    passes instead of ~6 x B Python-level array ops.  Any object-form
    commit or precondition violation falls back to the per-block path so
    results and errors match exactly.

    Returns (templates[T,128], tmpl_idx[N], sigs[N,64], idxs[N],
    counts[B], tallied[B], foreign[B]): the first four are the merged
    device batch exactly as `merge_commit_lanes` lays it out; the last
    three are per-block lane counts, tallied power for the expected
    block, and foreign (other non-nil block) power — everything the
    post-verify tally needs, with no per-block arrays retained.
    Structural errors raise `CommitFormatError` naming the height.
    """
    if not items:
        z = np.zeros(0, dtype=np.int64)
        return (np.zeros((0, canonical.SIGN_BYTES_LEN), dtype=np.uint8),
                np.zeros(0, dtype=np.int32),
                np.zeros((0, 64), dtype=np.uint8),
                np.zeros(0, dtype=np.int32), z, z.copy(), z.copy())
    if not _window_fast_eligible(val_set, items):
        arrays = []
        for bid, h, c in items:
            try:
                arrays.append(
                    val_set.commit_verify_lanes(chain_id, bid, h, c))
            except ValueError as e:
                # stale/malformed commit: surface the height so the
                # caller can blame the successor's deliverer
                raise CommitFormatError(h, str(e)) from None
        templates, tmpl_idx, sigs, idxs = merge_commit_lanes(arrays)
        counts = np.asarray([len(a[4]) for a in arrays], dtype=np.int64)
        tallied = np.asarray([int(a[3].sum()) for a in arrays],
                             dtype=np.int64)
        foreign = np.asarray([a[5] for a in arrays], dtype=np.int64)
        return templates, tmpl_idx, sigs, idxs, counts, tallied, foreign
    b = len(items)
    heights = np.fromiter((c.height_ for _, _, c in items), np.int64, b)
    rounds = np.fromiter((c.round_ for _, _, c in items), np.int64, b)
    totals = np.fromiter((c.block_id.parts.total for _, _, c in items),
                         np.int64, b)
    bh = np.frombuffer(b"".join(c.block_id.hash for _, _, c in items),
                       np.uint8).reshape(b, 32)
    ph = np.frombuffer(b"".join(c.block_id.parts.hash for _, _, c in items),
                       np.uint8).reshape(b, 32)
    templates = canonical.batch_sign_bytes(
        chain_id, np.full(b, canonical.TYPE_PRECOMMIT, dtype=np.int64),
        heights, rounds, bh, ph, totals)
    present = np.stack([c.present for _, _, c in items])    # bool[B,V]
    # row-major nonzero == per-block flatnonzero, already in merge order
    lane_b, lane_v = np.nonzero(present)
    idxs = lane_v.astype(np.int32)
    tmpl_idx = lane_b.astype(np.int32)   # one template per compact commit
    all_sigs = np.stack([c.sigs for _, _, c in items])      # uint8[B,V,64]
    sigs = np.ascontiguousarray(all_sigs[lane_b, lane_v])
    counts = present.sum(axis=1, dtype=np.int64)
    powers = np.where(present, val_set._powers_arr()[np.newaxis, :], 0)
    row_power = powers.sum(axis=1, dtype=np.int64)
    same = np.fromiter(
        (c.block_id.key() == bid.key() for bid, _, c in items), bool, b)
    # validate_basic already rejects nil compact commits, so every
    # non-matching commit endorses a foreign non-nil block
    tallied = np.where(same, row_power, 0)
    foreign = np.where(same, 0, row_power)
    return templates, tmpl_idx, sigs, idxs, counts, tallied, foreign


def window_tally_check(items: list[tuple], ok: np.ndarray,
                       counts: np.ndarray, tallied: np.ndarray,
                       foreign: np.ndarray, total: int) -> None:
    """Post-verify window tally, vectorized: raise the canonical
    per-height error for the FIRST block (in window order) whose lanes
    fail or whose tallied power misses +2/3 — identical blame semantics
    to the per-block loop it replaces."""
    bounds = np.cumsum(counts)
    if not ok.all():
        lane = int(np.argmin(ok))
        blk = int(np.searchsorted(bounds, lane, side="right"))
        first = int(bounds[blk - 1]) if blk else 0
        h = items[blk][1]
        raise CommitSignatureError(h, int(np.argmin(ok[first:bounds[blk]])))
    short = np.flatnonzero(~(tallied * 3 > total * 2))
    if len(short):
        blk = int(short[0])
        h = items[blk][1]
        raise CommitPowerError(
            h, int(tallied[blk]), total,
            _foreign_explains_shortfall(int(tallied[blk]),
                                        int(foreign[blk]), total))


def verify_commits_batched(val_set: ValidatorSet, chain_id: str,
                           items: list[tuple],
                           producer: str = "fastsync",
                           klass: str | None = None) -> None:
    """Verify MANY commits against one validator set in a single device
    call — the fast-sync window (`items` = [(block_id, height, commit)]).

    This is the framework's generalization of the reference SYNC_LOOP's
    one-at-a-time `Validators.VerifyCommit`
    (reference `blockchain/reactor.go:230-231`): all (block x validator)
    signature lanes flatten into one batch so the device sees a single
    large verify instead of K small ones.  Lane assembly and the
    post-verify tally are window-vectorized (`window_commit_lanes`) so
    the host never loops per block on the hot path.  Raises ValueError
    naming the first failing height.
    """
    from tendermint_tpu import batchplane
    if not items:
        return
    templates, tmpl_idx, sigs, idxs, counts, tallied, foreign = \
        window_commit_lanes(val_set, chain_id, items)
    ok = batchplane.verify_grouped_templated(
        val_set.set_key(), val_set.pubs_matrix(), idxs,
        tmpl_idx, templates, sigs, producer=producer,
        klass=klass or batchplane.CLASS_FASTSYNC)
    window_tally_check(items, ok, counts, tallied, foreign,
                       val_set.total_voting_power())


def _foreign_explains_shortfall(tallied: int, foreign_power: int,
                                total: int) -> bool:
    """Blame disambiguation for CommitPowerError: only call the block
    itself foreign (redo THIS height) when the power endorsing other
    non-nil blocks is large enough that, had those votes endorsed ours,
    the commit would have reached +2/3 — a lone Byzantine stray vote
    cannot redirect blame from a pruned LastCommit (whose fix is redoing
    height+1)."""
    return (tallied + foreign_power) * 3 > total * 2


def _neg_addr(addr: bytes) -> bytes:
    """Sort helper: max() prefers the lexicographically smallest address on
    accum ties, matching the reference's deterministic tie-break."""
    return bytes(255 - b for b in addr)
