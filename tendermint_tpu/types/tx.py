"""Transactions and their Merkle commitments.

Reference: `types/tx.go` — `Tx.Hash`, `Txs.Hash` (recursive binary Merkle
over wire bytes, `types/tx.go:29-43`), inclusion proofs (`:66-85`).
"""

from __future__ import annotations

from dataclasses import dataclass

from tendermint_tpu.types import merkle


class Tx(bytes):
    """An opaque transaction; the app defines its meaning."""

    @property
    def hash(self) -> bytes:
        return merkle.leaf_hash(self)


def txs_hash(txs: list[bytes]) -> bytes:
    """Merkle root over transactions (reference `types/tx.go:29-43`)."""
    return merkle.root(list(txs))


def txs_proof(txs: list[bytes], index: int) -> "TxProof":
    rt, proofs = merkle.proofs(list(txs))
    return TxProof(root=rt, tx=Tx(txs[index]), proof=proofs[index])


@dataclass(frozen=True)
class TxProof:
    """Inclusion proof of one tx in a block's data hash
    (reference `types/tx.go:96-109`)."""
    root: bytes
    tx: Tx
    proof: merkle.Proof

    def validate(self, data_hash: bytes) -> bool:
        if data_hash != self.root:
            return False
        if merkle.leaf_hash(self.tx) != self.proof.leaf:
            return False
        return self.proof.verify(self.root)
