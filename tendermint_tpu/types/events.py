"""Typed event bus: consensus progress published to RPC subscribers.

Reference: `types/events.go` over tmlibs/events — NewBlock, NewRound(Step),
Polka, (Un)Lock, Vote, Tx:<hash>, ProposalHeartbeat (`:13-35`), with an
`EventCache` that buffers during block finalization and flushes after
commit (`:175-177`; used `consensus/state.go:1317,1339`).

This implementation is a synchronous pub/sub with thread-safe subscribe /
fire; async delivery to websockets is layered on by the RPC server.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable

# -- event keys (reference types/events.go:13-35) -------------------------
NEW_BLOCK = "NewBlock"
NEW_BLOCK_HEADER = "NewBlockHeader"
NEW_ROUND_STEP = "NewRoundStep"
NEW_ROUND = "NewRound"
TIMEOUT_PROPOSE = "TimeoutPropose"
COMPLETE_PROPOSAL = "CompleteProposal"
POLKA = "Polka"
UNLOCK = "Unlock"
LOCK = "Lock"
RELOCK = "Relock"
TIMEOUT_WAIT = "TimeoutWait"
VOTE = "Vote"
PROPOSAL_HEARTBEAT = "ProposalHeartbeat"


def event_tx(tx_hash: bytes) -> str:
    """Per-tx event key (reference `types/events.go:19` EventStringTx)."""
    return f"Tx:{tx_hash.hex()}"


class EventSwitch:
    """Thread-safe pub/sub keyed by event string
    (tmlibs/events semantics: one callback per (subscriber, event))."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: dict[str, dict[str, Callable]] = defaultdict(dict)

    def subscribe(self, subscriber: str, event: str,
                  cb: Callable[[object], None]) -> None:
        with self._lock:
            self._subs[event][subscriber] = cb

    def unsubscribe(self, subscriber: str, event: str) -> None:
        with self._lock:
            self._subs.get(event, {}).pop(subscriber, None)

    def unsubscribe_all(self, subscriber: str) -> None:
        with self._lock:
            for subs in self._subs.values():
                subs.pop(subscriber, None)

    def fire(self, event: str, data: object = None) -> None:
        with self._lock:
            cbs = list(self._subs.get(event, {}).values())
        for cb in cbs:
            cb(data)


class EventCache:
    """Buffers fires until flush (reference `types/events.go:175-177`):
    consensus caches events raised during finalizeCommit and flushes them
    after the new state is committed."""

    def __init__(self, evsw: EventSwitch):
        self._evsw = evsw
        self._pending: list[tuple[str, object]] = []

    def fire(self, event: str, data: object = None) -> None:
        self._pending.append((event, data))

    def flush(self) -> None:
        pending, self._pending = self._pending, []
        for event, data in pending:
            self._evsw.fire(event, data)
