"""PartSet: blocks chunked into Merkle-proved parts for gossip.

Reference: `types/part_set.go` — serialized block split into 64KB parts
(`types/block.go:18-19,115-117`), each part hashed into a simple Merkle
tree with per-part inclusion proofs verified on receive
(`types/part_set.go:95-122,188-214`).  Different peers serve different
parts concurrently; the proof lets a receiver validate each part against
the proposal's PartSetHeader before assembly.

`from_data_batched` is the fast-sync path: the bulk hashing (full 64KB
part chunks, the dominant cost of re-hashing big blocks) runs as ONE
lockstep device batch, while the irregular work (short tail chunks, tree
and proof assembly) stays on the host — the reference re-hashes each
block serially on the CPU inside its sync loop
(`blockchain/reactor.go:224`, `types/part_set.go:95-122`).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from tendermint_tpu.types import merkle
from tendermint_tpu.types.codec import Reader, lp_bytes, u32

PART_SIZE = 64 * 1024  # reference types/block.go:19

# Below this many full-size chunks in a batch the host's C hashing wins
# (device dispatch + transfer overhead); above, lockstep lanes win.
DEVICE_MIN_CHUNKS = 16


@dataclass(frozen=True)
class PartSetHeader:
    total: int
    hash: bytes

    def is_zero(self) -> bool:
        return self.total == 0 and not self.hash

    def encode(self) -> bytes:
        return u32(self.total) + lp_bytes(self.hash)

    @classmethod
    def decode(cls, r: Reader) -> "PartSetHeader":
        return cls(total=r.u32(), hash=r.lp_bytes())

    def __str__(self):
        return f"{self.total}:{self.hash.hex()[:12]}"


ZERO_PSH = PartSetHeader(0, b"")


@dataclass(frozen=True)
class Part:
    index: int
    bytes_: bytes
    proof: merkle.Proof

    def verify(self, header: PartSetHeader) -> bool:
        if self.index != self.proof.index or self.proof.total != header.total:
            return False
        if merkle.leaf_hash(self.bytes_) != self.proof.leaf:
            return False
        return self.proof.verify(header.hash)

    def encode(self) -> bytes:
        out = u32(self.index) + lp_bytes(self.bytes_)
        out += u32(self.proof.total) + u32(self.proof.index)
        out += lp_bytes(self.proof.leaf) + u32(len(self.proof.aunts))
        for a in self.proof.aunts:
            out += lp_bytes(a)
        return out

    @classmethod
    def decode(cls, r: Reader) -> "Part":
        index = r.u32()
        data = r.lp_bytes()
        total, pidx = r.u32(), r.u32()
        leaf = r.lp_bytes()
        aunts = tuple(r.lp_bytes() for _ in range(r.u32()))
        return cls(index, data, merkle.Proof(total, pidx, leaf, aunts))


class PartSet:
    """A complete or in-progress set of parts for one block."""

    def __init__(self, header: PartSetHeader):
        self.header = header
        self._parts: list[Part | None] = [None] * header.total
        self._count = 0

    @classmethod
    def from_data(cls, data: bytes, part_size: int = PART_SIZE) -> "PartSet":
        """Chunk serialized block bytes into proved parts
        (reference `types/part_set.go:95-122`)."""
        return from_data_batched([data], part_size)[0]

    @classmethod
    def _assemble(cls, chunks: list[bytes],
                  leaf_hashes: list[bytes]) -> "PartSet":
        rt, proofs = merkle.proofs_from_leaf_hashes(leaf_hashes)
        ps = cls(PartSetHeader(len(chunks), rt))
        for i, (c, pr) in enumerate(zip(chunks, proofs)):
            ps._parts[i] = Part(i, c, pr)
        ps._count = len(chunks)
        return ps

    def add_part(self, part: Part) -> bool:
        """Verify against the header and store; False on invalid/duplicate
        index mismatch (reference `types/part_set.go:188-214`)."""
        if not (0 <= part.index < self.header.total):
            return False
        if self._parts[part.index] is not None:
            return False
        if not part.verify(self.header):
            return False
        self._parts[part.index] = part
        self._count += 1
        return True

    def get_part(self, index: int) -> Part | None:
        return self._parts[index]

    def has_part(self, index: int) -> bool:
        return self._parts[index] is not None

    @property
    def count(self) -> int:
        return self._count

    @property
    def total(self) -> int:
        return self.header.total

    def is_complete(self) -> bool:
        return self._count == self.header.total

    def bit_array(self) -> list[bool]:
        return [p is not None for p in self._parts]

    def assemble(self) -> bytes:
        assert self.is_complete()
        return b"".join(p.bytes_ for p in self._parts)


def _device_full_chunk_hashes(chunks: list[bytes],
                              part_size: int) -> list[bytes] | None:
    """Leaf-hash equal-size chunks in one lockstep device batch; None when
    the device would lose to host hashlib (small batch, no tpu backend)."""
    if len(chunks) < DEVICE_MIN_CHUNKS:
        return None
    from tendermint_tpu.crypto import backend as cb
    if cb.get_backend().name != "tpu":
        return None
    try:
        from tendermint_tpu.ops import merkle as dev_merkle
    except ImportError:                  # pragma: no cover - env dependent
        return None
    n = len(chunks)
    b = 1 << (n - 1).bit_length()        # pad count to a power of two so a
    pad = b - n                          # few compiled shapes cover any load
    arr = np.frombuffer(b"".join(chunks) + b"\x00" * (pad * part_size),
                        np.uint8).reshape(b, part_size)
    h = np.asarray(dev_merkle.leaf_hashes_jit(arr))
    return [h[i].tobytes() for i in range(n)]


def from_data_batched(datas: list[bytes],
                      part_size: int = PART_SIZE) -> list["PartSet"]:
    """Build PartSets for MANY serialized blocks at once.

    All full-size (== part_size) chunks across the whole window are leaf-
    hashed in one device batch; short tail chunks and the per-block
    tree/proof assembly stay host-side.  Falls back to host hashing
    entirely when the batch is too small to beat hashlib.
    """
    per_block: list[list[bytes]] = []
    full: list[tuple[int, int]] = []     # (block, part) of full chunks
    full_chunks: list[bytes] = []
    for bi, data in enumerate(datas):
        chunks = [data[i:i + part_size]
                  for i in range(0, len(data), part_size)] or [b""]
        per_block.append(chunks)
        for pi, c in enumerate(chunks):
            if len(c) == part_size:
                full.append((bi, pi))
                full_chunks.append(c)
    hashes: list[list[bytes | None]] = [[None] * len(c) for c in per_block]
    dev = _device_full_chunk_hashes(full_chunks, part_size)
    if dev is None and len(full_chunks) >= DEVICE_MIN_CHUNKS:
        # native threaded C++ engine for the bulk when the device path
        # declined (no tpu backend / toolchain-built lib available)
        from tendermint_tpu.utils import nativelib
        arr = nativelib.leaf_hashes(np.frombuffer(
            b"".join(full_chunks), np.uint8).reshape(-1, part_size))
        if arr is not None:
            dev = [arr[i].tobytes() for i in range(len(full_chunks))]
    if dev is not None:
        for (bi, pi), h in zip(full, dev):
            hashes[bi][pi] = h
    out = []
    for bi, chunks in enumerate(per_block):
        lh = [h if h is not None else merkle.leaf_hash(c)
              for c, h in zip(chunks, hashes[bi])]
        out.append(PartSet._assemble(chunks, lh))
    return out
