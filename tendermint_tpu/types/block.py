"""Block, Header, Commit, BlockID — the replicated data structures.

Reference: `types/block.go` — Block = Header + Data(Txs) + LastCommit
(`:23-27`), `Header.Hash` = Merkle-of-map over fields (`:178-193`),
`Commit.Hash` = Merkle over precommit signatures (`:345-354`),
`ValidateBasic` structural checks (`:53-90`).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tendermint_tpu.types import merkle
from tendermint_tpu.types.codec import (Reader, i64, lp_bytes, u32, u64, u8)
from tendermint_tpu.types.part_set import PartSet, PartSetHeader, ZERO_PSH
from tendermint_tpu.types.tx import txs_hash
from tendermint_tpu.types.vote import Vote

MAX_BLOCK_SIZE_TXS = 10_000   # reference config/config.go:373


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    parts: PartSetHeader = ZERO_PSH

    def is_zero(self) -> bool:
        return not self.hash and self.parts.is_zero()

    def key(self) -> tuple:
        return (self.hash, self.parts.total, self.parts.hash)

    def encode(self) -> bytes:
        return lp_bytes(self.hash) + self.parts.encode()

    @classmethod
    def decode(cls, r: Reader) -> "BlockID":
        return cls(hash=r.lp_bytes(), parts=PartSetHeader.decode(r))

    def __str__(self):
        return f"{self.hash.hex()[:12]}@{self.parts}"


ZERO_BLOCK_ID = BlockID()


@dataclass(frozen=True)
class Header:
    chain_id: str
    height: int
    time_ns: int                    # unix nanos; proposer's clock
    num_txs: int
    last_block_id: BlockID
    last_commit_hash: bytes
    data_hash: bytes
    validators_hash: bytes
    app_hash: bytes

    def hash(self) -> bytes:
        """Merkle-of-map over the fields (reference `types/block.go:178-193`).
        Empty for the pre-genesis header (no validators hash yet)."""
        if not self.validators_hash:
            return b""
        return merkle.root_of_map({
            "app": self.app_hash,
            "chain_id": self.chain_id.encode(),
            "data": self.data_hash,
            "height": u64(self.height),
            "last_block_id": self.last_block_id.encode(),
            "last_commit": self.last_commit_hash,
            "num_txs": u64(self.num_txs),
            "time": i64(self.time_ns),
            "validators": self.validators_hash,
        })

    def encode(self) -> bytes:
        cid = self.chain_id.encode()
        return (lp_bytes(cid) + u64(self.height) + i64(self.time_ns) +
                u64(self.num_txs) + self.last_block_id.encode() +
                lp_bytes(self.last_commit_hash) + lp_bytes(self.data_hash) +
                lp_bytes(self.validators_hash) + lp_bytes(self.app_hash))

    @classmethod
    def decode(cls, r: Reader) -> "Header":
        return cls(chain_id=r.lp_bytes().decode(), height=r.u64(),
                   time_ns=r.i64(), num_txs=r.u64(),
                   last_block_id=BlockID.decode(r),
                   last_commit_hash=r.lp_bytes(), data_hash=r.lp_bytes(),
                   validators_hash=r.lp_bytes(), app_hash=r.lp_bytes())


@dataclass
class Commit:
    """+2/3 precommits for one block (reference `types/block.go:288-354`).

    `precommits` is validator-index-aligned with the validator set that
    signed it; absent votes are None.
    """
    block_id: BlockID
    precommits: list[Vote | None]

    _hash: bytes | None = field(default=None, repr=False, compare=False)
    _bit_array: list[bool] | None = field(default=None, repr=False,
                                          compare=False)

    def height(self) -> int:
        for v in self.precommits:
            if v is not None:
                return v.height
        return 0

    def round(self) -> int:
        for v in self.precommits:
            if v is not None:
                return v.round
        return 0

    def size(self) -> int:
        return len(self.precommits)

    def num_sigs(self) -> int:
        return sum(1 for v in self.precommits if v is not None)

    def is_commit(self) -> bool:
        return bool(self.precommits)

    def bit_array(self) -> list[bool]:
        if self._bit_array is None:
            self._bit_array = [v is not None for v in self.precommits]
        return self._bit_array

    def hash(self) -> bytes:
        """Merkle over the precommit signatures
        (reference `types/block.go:345-354`)."""
        if self._hash is None:
            items = [(v.signature if v is not None else b"")
                     for v in self.precommits]
            self._hash = merkle.root(items)
        return self._hash

    def validate_basic(self) -> None:
        """Structural checks (reference `types/block.go:307-331`)."""
        if self.block_id.is_zero():
            raise ValueError("commit with zero block id")
        if not self.precommits:
            raise ValueError("commit with no precommits")
        height, round_ = self.height(), self.round()
        from tendermint_tpu.types.canonical import TYPE_PRECOMMIT
        for i, v in enumerate(self.precommits):
            if v is None:
                continue
            if v.type != TYPE_PRECOMMIT:
                raise ValueError(f"commit vote {i} is not a precommit")
            if v.height != height or v.round != round_:
                raise ValueError(f"commit vote {i} has wrong height/round")

    def encode(self) -> bytes:
        out = self.block_id.encode() + u32(len(self.precommits))
        for v in self.precommits:
            if v is None:
                out += u8(0)
            else:
                out += u8(1) + v.encode()
        return out

    @classmethod
    def decode(cls, r: Reader) -> "Commit":
        block_id = BlockID.decode(r)
        n = r.u32()
        votes: list[Vote | None] = []
        for _ in range(n):
            votes.append(Vote.decode(r) if r.u8() else None)
        return cls(block_id=block_id, precommits=votes)


EMPTY_COMMIT = Commit(block_id=ZERO_BLOCK_ID, precommits=[])


@dataclass
class CompactCommit:
    """Array-native commit: the device plane's representation.

    A +2/3 commit whose signatures live as ONE uint8[V, 64] matrix with
    a presence bitmap instead of V `Vote` objects — the form the batched
    verifier consumes directly (`ValidatorSet.commit_verify_lanes`
    accepts either).  At fast-sync scale the object form is real cost:
    100k blocks x 100 validators is 10M Vote objects (~5 GB of heap and
    tens of seconds of construction) whose fields the verify plane
    immediately re-flattens into exactly these arrays.  All lanes share
    the commit's (height, round, block_id) — the common case fast-sync
    stores; commits with stray foreign/nil votes keep the object form.

    Conversions are lossless both ways for same-block commits; the wire
    codec stays `Commit` (this is an in-memory/device layout, not a new
    wire type).
    """
    block_id: "BlockID"
    height_: int
    round_: int
    sigs: "object"           # np.uint8[V, 64]
    present: "object"        # np.bool_[V]

    def height(self) -> int:
        return self.height_

    def round(self) -> int:
        return self.round_

    def size(self) -> int:
        return len(self.present)

    def num_sigs(self) -> int:
        return int(self.present.sum())

    def is_commit(self) -> bool:
        return self.num_sigs() > 0

    def bit_array(self) -> list[bool]:
        return [bool(b) for b in self.present]

    def validate_basic(self) -> None:
        if self.block_id.is_zero():
            raise ValueError("commit with zero block id")
        if self.size() == 0:
            raise ValueError("commit with no precommits")
        if self.sigs.shape != (self.size(), 64):
            raise ValueError("sigs matrix shape mismatch")

    def to_commit(self, val_set) -> Commit:
        """Expand to the Vote-object form (for wire encoding / stores)."""
        from tendermint_tpu.types.canonical import TYPE_PRECOMMIT
        votes: list[Vote | None] = []
        for i in range(self.size()):
            if not self.present[i]:
                votes.append(None)
                continue
            votes.append(Vote(
                validator_address=val_set.validators[i].address,
                validator_index=i, height=self.height_, round=self.round_,
                type=TYPE_PRECOMMIT, block_id=self.block_id,
                signature=self.sigs[i].tobytes()))
        return Commit(block_id=self.block_id, precommits=votes)

    @classmethod
    def from_commit(cls, commit: Commit) -> "CompactCommit | None":
        """Compact a same-block commit; None if any vote targets a
        different block (foreign/nil strays need the object form)."""
        import numpy as np
        n = commit.size()
        if n == 0:
            return None
        key = commit.block_id.key()
        sigs = np.zeros((n, 64), dtype=np.uint8)
        present = np.zeros(n, dtype=bool)
        for i, v in enumerate(commit.precommits):
            if v is None:
                continue
            if v.block_id.key() != key or len(v.signature) != 64:
                return None
            sigs[i] = np.frombuffer(v.signature, np.uint8)
            present[i] = True
        return cls(block_id=commit.block_id, height_=commit.height(),
                   round_=commit.round(), sigs=sigs, present=present)


@dataclass
class Block:
    header: Header
    txs: list[bytes]
    last_commit: Commit

    _hash: bytes | None = field(default=None, repr=False, compare=False)
    # blocks are value objects: the serialization is cached (and seeded
    # with the wire bytes on decode) so fast-sync's part-set re-hash does
    # not re-encode a 100-vote commit per block
    _encoded: bytes | None = field(default=None, repr=False, compare=False)

    @classmethod
    def make(cls, chain_id: str, height: int, time_ns: int, txs: list[bytes],
             last_commit: Commit, last_block_id: BlockID,
             validators_hash: bytes, app_hash: bytes) -> "Block":
        """Assemble a block with derived hashes
        (reference `types/block.go:31-50` MakeBlock)."""
        header = Header(
            chain_id=chain_id, height=height, time_ns=time_ns,
            num_txs=len(txs), last_block_id=last_block_id,
            last_commit_hash=(last_commit.hash() if last_commit.is_commit()
                              else b""),
            data_hash=txs_hash(txs), validators_hash=validators_hash,
            app_hash=app_hash)
        return cls(header=header, txs=list(txs), last_commit=last_commit)

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = self.header.hash()
        return self._hash

    @property
    def height(self) -> int:
        return self.header.height

    def validate_basic(self) -> None:
        """Structural self-consistency (reference `types/block.go:53-90`)."""
        h = self.header
        if h.height < 1:
            raise ValueError("block height < 1")
        if h.num_txs != len(self.txs):
            raise ValueError("num_txs mismatch")
        if h.data_hash != txs_hash(self.txs):
            raise ValueError("data hash mismatch")
        if h.height == 1:
            if self.last_commit.is_commit():
                raise ValueError("first block must have empty last commit")
            if h.last_commit_hash:
                raise ValueError("first block last_commit_hash must be empty")
        else:
            if h.last_commit_hash != self.last_commit.hash():
                raise ValueError("last_commit_hash mismatch")
            self.last_commit.validate_basic()

    def encode(self) -> bytes:
        if self._encoded is None:
            out = self.header.encode()
            out += u32(len(self.txs))
            for tx in self.txs:
                out += lp_bytes(tx)
            out += self.last_commit.encode()
            self._encoded = out
        return self._encoded

    @classmethod
    def decode_bytes(cls, data: bytes) -> "Block":
        r = Reader(data)
        header = Header.decode(r)
        txs = [r.lp_bytes() for _ in range(r.u32())]
        last_commit = Commit.decode(r)
        r.expect_done()
        blk = cls(header=header, txs=txs, last_commit=last_commit)
        blk._encoded = data   # deterministic codec: decode/encode roundtrip
        return blk

    def make_part_set(self, part_size: int | None = None) -> PartSet:
        """Serialize and chunk (reference `types/block.go:115-117`)."""
        from tendermint_tpu.types.part_set import PART_SIZE
        return PartSet.from_data(self.encode(), part_size or PART_SIZE)

    def block_id(self, part_set: PartSet | None = None) -> BlockID:
        ps = part_set or self.make_part_set()
        return BlockID(hash=self.hash(), parts=ps.header)

    def __str__(self):
        return (f"Block#{self.header.height}"
                f"[{len(self.txs)} txs, hash {self.hash().hex()[:12]}]")
