"""Consensus doctor: name the largest thief per height range.

Same contract as `bench.py --doctor` (utils/attribution.doctor_report)
but over the LIVE timeline: each height's wall clock is partitioned by
the four lifecycle stages (sums-to-wall by construction), and the
doctor aggregates contiguous height ranges, maps stages onto named
thieves, and points at the guilty node:

- `slow_proposer`        — propose stage (waiting for the proposal)
- `quorum_straggler`     — prevote + precommit stages (quorum forming)
- `commit_apply`         — commit stage (parts completion + ApplyBlock)
- `batchplane_queue_wait`— vote-verify wait inside the quorum stages;
                           a COMPETITOR like attribution's
                           half_full_batches: it steals from inside the
                           partition, it does not add to the sum
- `gossip_delay`         — per-receiver serialized fan-out wait
                           (mesh gossip stats), also a competitor

The partition residual is carried per range so a consumer can verify
the invariant instead of trusting it.
"""

from __future__ import annotations

from tendermint_tpu.telemetry.collector import STAGES

CONSENSUS_DOCTOR_SCHEMA = "tpu-bft-consensus-doctor/1"

# stage -> partition thief (competitors are added separately)
_STAGE_THIEF = {"propose": "slow_proposer",
                "prevote": "quorum_straggler",
                "precommit": "quorum_straggler",
                "commit": "commit_apply"}
_RESIDUAL_TOL = 1e-6


def _chunk(heights: list[dict], range_len: int) -> list[list[dict]]:
    out, cur = [], []
    for row in heights:
        if cur and (row["height"] - cur[0]["height"] >= range_len or
                    row["height"] != cur[-1]["height"] + 1):
            out.append(cur)
            cur = []
        cur.append(row)
    if cur:
        out.append(cur)
    return out


def consensus_doctor(timeline: dict, range_len: int = 10) -> dict:
    """Machine-readable report over a merged timeline
    (`collector.build_timeline`).  Ranges are contiguous height chunks
    of at most `range_len`; each names its largest thief and the
    straggler / slow-proposer nodes behind the quorum stages."""
    heights = list(timeline.get("heights", ()))
    gossip = timeline.get("gossip") or {}
    total_wall = sum(r["wall_s"] for r in heights) or 0.0
    gossip_total = float(gossip.get("per_receiver_wait_s", 0.0))
    ranges = []
    residual_max = 0.0
    for chunk in _chunk(heights, range_len):
        stages = {s: 0.0 for s in STAGES}
        verify_wait = 0.0
        wall = 0.0
        lag_by_node: dict[str, float] = {}
        propose_by_node: dict[str, float] = {}
        residual = 0.0
        for row in chunk:
            wall += row["wall_s"]
            verify_wait += row["verify_wait_s"]
            for s in STAGES:
                stages[s] += row["stages"][s]
            residual = max(residual, abs(
                sum(row["stages"].values()) - row["wall_s"]))
            for node, cell in row.get("nodes", {}).items():
                lag_by_node[node] = (lag_by_node.get(node, 0.0) +
                                     cell["t_commit"] - row["t_commit"])
                propose_by_node[node] = (propose_by_node.get(node, 0.0) +
                                         cell["stages"]["propose"])
        residual_max = max(residual_max, residual)
        thieves = {"slow_proposer": 0.0, "quorum_straggler": 0.0,
                   "commit_apply": 0.0}
        for s, v in stages.items():
            thieves[_STAGE_THIEF[s]] += v
        # competitors: steal from INSIDE the stages, so they race the
        # partition components without being part of the sum
        thieves["batchplane_queue_wait"] = verify_wait
        thieves["gossip_delay"] = (gossip_total * wall / total_wall
                                   if total_wall > 0 else 0.0)
        largest = max(thieves, key=thieves.get)
        straggler = max(lag_by_node, key=lag_by_node.get, default=None)
        slow_prop = max(propose_by_node, key=propose_by_node.get,
                        default=None)
        ranges.append({
            "heights": [chunk[0]["height"], chunk[-1]["height"]],
            "wall_s": wall,
            "stages": stages,
            "partition_residual_s": residual,
            "verify_wait_s": verify_wait,
            "thieves": thieves,
            "largest_thief": largest,
            "largest_thief_s": thieves[largest],
            "straggler_node": straggler,
            "straggler_lag_s": lag_by_node.get(straggler, 0.0),
            "slowest_propose_node": slow_prop,
        })
    global_thieves: dict[str, float] = {}
    for r in ranges:
        for k, v in r["thieves"].items():
            global_thieves[k] = global_thieves.get(k, 0.0) + v
    largest = (max(global_thieves, key=global_thieves.get)
               if global_thieves else None)
    return {
        "schema": CONSENSUS_DOCTOR_SCHEMA,
        "nodes": timeline.get("nodes", []),
        "height_range": timeline.get("height_range", [0, 0]),
        "height_count": len(heights),
        "wall_s": total_wall,
        "stage_stats": timeline.get("stage_stats", {}),
        "wall_p99": timeline.get("wall_p99", 0.0),
        "ranges": ranges,
        "thieves": global_thieves,
        "largest_thief": largest,
        "largest_thief_s": global_thieves.get(largest, 0.0),
        "partition_residual_s": residual_max,
        "sums_to_wall": residual_max <= _RESIDUAL_TOL,
        "gossip": gossip,
    }


def render_consensus_report(report: dict) -> str:
    """Human-readable rendering of a consensus_doctor report."""
    lines = []
    lo, hi = report.get("height_range", [0, 0])
    lines.append(
        f"consensus doctor: heights {lo}..{hi} "
        f"({report.get('height_count', 0)} committed, "
        f"{report.get('wall_s', 0.0):.3f}s wall, "
        f"{len(report.get('nodes', []))} nodes)")
    ok = "holds" if report.get("sums_to_wall") else "VIOLATED"
    lines.append(f"  sums-to-wall {ok} "
                 f"(max residual {report.get('partition_residual_s', 0):.2e})")
    for s, st in report.get("stage_stats", {}).items():
        lines.append(f"  stage {s:<9s} p50 {st['p50']*1e3:8.1f}ms  "
                     f"p99 {st['p99']*1e3:8.1f}ms  "
                     f"total {st['total_s']:8.3f}s")
    if report.get("largest_thief"):
        lines.append(f"  largest thief: {report['largest_thief']} "
                     f"({report.get('largest_thief_s', 0.0):.3f}s)")
    for r in report.get("ranges", ()):
        a, b = r["heights"]
        who = r.get("straggler_node")
        extra = f", straggler {who}" if who else ""
        lines.append(f"  [{a}..{b}] wall {r['wall_s']:.3f}s -> "
                     f"{r['largest_thief']} "
                     f"({r['largest_thief_s']:.3f}s{extra})")
    g = report.get("gossip") or {}
    if g.get("count"):
        lines.append(f"  gossip: {g['count']} deliveries, "
                     f"p99 {g.get('p99', 0.0)*1e3:.2f}ms, "
                     f"worst link {g.get('worst_link')} "
                     f"({g.get('max_s', 0.0)*1e3:.2f}ms)")
    return "\n".join(lines)
