"""Consensus timeline plane: mesh-wide lifecycle aggregation.

The flight recorder (utils/tracing.py) and attribution partition
(utils/attribution.py) account for where a REPLAY window's wall clock
goes; this package does the same for LIVE multi-node consensus.  Every
node's ConsensusState closes a per-height lifecycle record at its
commit site (consensus/state.py `_finish_height`); the collector here
merges those records across a rig into one per-height waterfall with
clock-skew normalization, the doctor names the largest per-stage thief,
and `to_chrome_trace` renders one track per node for Perfetto.

Surfaces: `cli timeline`, the unsafe-gated `debug_timeline` RPC route,
chaos artifact bundles, and the stage-level budgets live-rounds grades.
"""

from tendermint_tpu.telemetry.collector import (STAGES, TIMELINE_SCHEMA,
                                                build_timeline,
                                                collect_mesh, feed_registry,
                                                merge_dumps,
                                                normalize_record,
                                                records_from_spans,
                                                to_chrome_trace)
from tendermint_tpu.telemetry.doctor import (CONSENSUS_DOCTOR_SCHEMA,
                                             consensus_doctor,
                                             render_consensus_report)

__all__ = [
    "STAGES", "TIMELINE_SCHEMA", "build_timeline", "collect_mesh",
    "feed_registry", "merge_dumps", "normalize_record",
    "records_from_spans", "to_chrome_trace",
    "CONSENSUS_DOCTOR_SCHEMA", "consensus_doctor",
    "render_consensus_report",
]
