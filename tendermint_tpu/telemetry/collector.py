"""Mesh collector: merge per-node height lifecycles into one waterfall.

Input is the canonical lifecycle record `_finish_height` emits::

    {"node": "n3", "height": 7, "round": 0, "proposer": "ab12..",
     "t_start": ..., "t_proposal": ..., "t_prevote": ...,
     "t_precommit": ..., "t_commit": ..., "verify_wait_s": ...}

The five timestamps are a monotone cut sequence, so the four stage
durations (STAGES) partition [t_start, t_commit] exactly — the merge
preserves that sums-to-wall invariant per node and the timeline's
representative row inherits it (`utils/attribution.py` discipline).

Records arrive three ways: in-process from a WireMesh rig
(`collect_mesh`), over RPC as per-node dumps with a wall-clock sample
for skew normalization (`merge_dumps`), or offline by re-deriving them
from the `consensus.stage.*` spans in a dumped Chrome trace
(`records_from_spans`).  Malformed input degrades PER NODE/RECORD —
a truncated dump drops that node's rows, never the mesh waterfall.
"""

from __future__ import annotations

from tendermint_tpu.utils import tracing
from tendermint_tpu.utils.metrics import REGISTRY

TIMELINE_SCHEMA = "tpu-bft-timeline/1"

# stage k spans [CUTS[k], CUTS[k+1]] of the record's timestamp sequence
STAGES = ("propose", "prevote", "precommit", "commit")
_CUTS = ("t_start", "t_proposal", "t_prevote", "t_precommit", "t_commit")


def percentile(vals: list[float], q: float) -> float:
    """Exact empirical quantile (same index rule as the WireMesh
    commit_latency_p99): 0.0 on empty input."""
    if not vals:
        return 0.0
    s = sorted(vals)
    return s[min(len(s) - 1, int(q * len(s)))]


def normalize_record(raw, offset_s: float = 0.0) -> dict | None:
    """Canonicalize one lifecycle record: coerce types, shift timestamps
    onto the collector's clock axis (minus `offset_s`), and re-clamp the
    cut sequence monotone.  None for anything malformed — the caller
    degrades per record, never corrupts the merge."""
    if not isinstance(raw, dict):
        return None
    try:
        rec = {
            "node": str(raw.get("node", "")),
            "height": int(raw["height"]),
            "round": int(raw.get("round", 0)),
            "proposer": str(raw.get("proposer", "")),
            "verify_wait_s": max(0.0, float(raw.get("verify_wait_s", 0.0))),
        }
        cuts = [float(raw[k]) - offset_s for k in _CUTS]
    except (KeyError, TypeError, ValueError):
        return None
    if rec["height"] < 1:
        return None
    for i in range(1, len(cuts)):
        cuts[i] = min(max(cuts[i], cuts[i - 1]), cuts[-1])
    if cuts[-1] < cuts[0]:
        return None
    rec.update(zip(_CUTS, cuts))
    return rec


def stage_durations(rec: dict) -> dict[str, float]:
    return {s: rec[hi] - rec[lo]
            for s, lo, hi in zip(STAGES, _CUTS, _CUTS[1:])}


def merge_dumps(dumps, ref_wall: float | None = None) -> dict:
    """Merge per-node dumps `{"node", "records", "wall_now"}` into one
    record list with clock-skew normalization.

    All dumps are collected at (approximately) one instant, so each
    node's `wall_now` SHOULD agree; the spread IS the clock skew.  The
    reference is `ref_wall` (the collector's own clock) or, absent
    that, the median wall_now; each node's records shift by its offset
    from the reference.  A node with no usable wall_now merges
    unshifted; a node whose records are missing/garbage is dropped and
    named in `dropped` — degrade per node, never corrupt the mesh.
    Duplicate (node, height) rows keep the earliest commit."""
    walls = []
    for d in dumps:
        try:
            walls.append(float(d["wall_now"]))
        except (KeyError, TypeError, ValueError):
            pass
    if ref_wall is None:
        ref_wall = percentile(walls, 0.5) if walls else 0.0
    records: dict[tuple[str, int], dict] = {}
    offsets: dict[str, float] = {}
    dropped: dict[str, str] = {}
    for i, d in enumerate(dumps):
        if not isinstance(d, dict):
            dropped[f"dump{i}"] = "not a dict"
            continue
        node = str(d.get("node") or f"dump{i}")
        try:
            off = float(d["wall_now"]) - ref_wall
        except (KeyError, TypeError, ValueError):
            off = 0.0
        raws = d.get("records")
        if not isinstance(raws, (list, tuple)) or not raws:
            dropped[node] = "empty or truncated record list"
            continue
        kept = 0
        for raw in raws:
            rec = normalize_record(raw, offset_s=off)
            if rec is None:
                continue
            if not rec["node"]:
                rec["node"] = node
            key = (rec["node"], rec["height"])
            cur = records.get(key)
            if cur is None or rec["t_commit"] < cur["t_commit"]:
                records[key] = rec
            kept += 1
        if kept:
            offsets[node] = off
        else:
            dropped[node] = "no valid records"
    return {"records": sorted(records.values(),
                              key=lambda r: (r["height"], r["node"])),
            "offsets": offsets, "dropped": dropped, "ref_wall": ref_wall}


def records_from_spans(spans) -> list[dict]:
    """Rebuild lifecycle records from `consensus.stage.*` /
    `consensus.height` flight-recorder spans (snapshot() or
    spans_from_chrome form) — the offline path for dumped traces."""
    by_key: dict[tuple[str, int], dict] = {}
    extra: dict[tuple[str, int], dict] = {}
    for s in spans:
        name = s.get("name", "")
        args = s.get("args") or {}
        if "height" not in args:
            continue
        try:
            key = (str(args.get("node", "")), int(args["height"]))
        except (TypeError, ValueError):
            continue
        if name == "consensus.height":
            extra[key] = {
                "round": args.get("round", 0),
                "proposer": args.get("proposer", ""),
                "verify_wait_s": args.get("verify_wait_s", 0.0)}
        elif name.startswith("consensus.stage."):
            stage = name[len("consensus.stage."):]
            if stage in STAGES:
                by_key.setdefault(key, {})[stage] = (
                    float(s.get("ts", 0.0)), float(s.get("dur", 0.0)))
    out = []
    for (node, height), stages in by_key.items():
        if len(stages) != len(STAGES):
            continue                       # truncated ring: partial height
        raw = {"node": node, "height": height,
               "t_start": stages["propose"][0]}
        t = stages["propose"][0]
        for stage, cut in zip(STAGES, _CUTS[1:]):
            ts, dur = stages[stage]
            t = max(t, ts + dur)
            raw[cut] = t
        raw.update(extra.get((node, height), {}))
        rec = normalize_record(raw)
        if rec is not None:
            out.append(rec)
    out.sort(key=lambda r: (r["height"], r["node"]))
    return out


def build_timeline(records, gossip: dict | None = None) -> dict:
    """The merged per-height waterfall.  Each height row carries every
    node's stage partition plus mesh aggregates; the representative is
    the FIRST committer (the node that defined the quorum's commit
    time), so the row's headline stages sum to its wall exactly."""
    rows: dict[int, list[dict]] = {}
    for rec in records:
        rows.setdefault(rec["height"], []).append(rec)
    heights = []
    stage_vals: dict[str, list[float]] = {s: [] for s in STAGES}
    wall_vals: list[float] = []
    for h in sorted(rows):
        per_node = {}
        rep = min(rows[h], key=lambda r: r["t_commit"])
        last = max(rows[h], key=lambda r: r["t_commit"])
        for rec in rows[h]:
            durs = stage_durations(rec)
            per_node[rec["node"]] = {
                "round": rec["round"],
                "t_start": rec["t_start"],
                "t_commit": rec["t_commit"],
                "wall_s": rec["t_commit"] - rec["t_start"],
                "stages": durs,
                "verify_wait_s": rec["verify_wait_s"],
            }
            for s, v in durs.items():
                stage_vals[s].append(v)
            wall_vals.append(rec["t_commit"] - rec["t_start"])
        heights.append({
            "height": h,
            "round": rep["round"],
            "proposer": rep["proposer"],
            "first_commit_node": rep["node"],
            "t_start": rep["t_start"],
            "t_commit": rep["t_commit"],
            "wall_s": rep["t_commit"] - rep["t_start"],
            "stages": stage_durations(rep),
            "verify_wait_s": rep["verify_wait_s"],
            "commit_spread_s": last["t_commit"] - rep["t_commit"],
            "last_commit_node": last["node"],
            "nodes": per_node,
        })
    stage_stats = {
        s: {"count": len(v), "total_s": sum(v),
            "p50": percentile(v, 0.50), "p99": percentile(v, 0.99)}
        for s, v in stage_vals.items()}
    return {
        "schema": TIMELINE_SCHEMA,
        "nodes": sorted({r["node"] for r in records}),
        "height_range": ([heights[0]["height"], heights[-1]["height"]]
                         if heights else [0, 0]),
        "heights": heights,
        "stage_stats": stage_stats,
        "wall_p99": percentile(wall_vals, 0.99),
        "gossip": gossip or {},
    }


def collect_mesh(mesh) -> dict:
    """One-call in-process collection from a WireMesh rig: lifecycle
    records (already on one clock — same process) + gossip fan-out
    stats into a timeline."""
    records = [r for r in (normalize_record(x)
                           for x in mesh.timeline_records())
               if r is not None]
    gossip = mesh.gossip_stats() if hasattr(mesh, "gossip_stats") else {}
    return build_timeline(records, gossip=gossip)


def feed_registry(timeline: dict) -> None:
    """Mirror a merged timeline onto /metrics: per-stage duration
    histograms (`consensus_stage_seconds{stage}`) and each node's last
    committed height (`timeline_node_height{node}`)."""
    last: dict[str, int] = {}
    for row in timeline.get("heights", ()):
        for node, cell in row.get("nodes", {}).items():
            for stage, dur in cell["stages"].items():
                REGISTRY.consensus_stage_seconds.labels(stage).observe(dur)
            if row["height"] > last.get(node, 0):
                last[node] = row["height"]
    for node, h in last.items():
        REGISTRY.timeline_node_height.labels(node).set(h)


def to_chrome_trace(timeline: dict) -> dict:
    """Chrome trace-event JSON with ONE TRACK PER NODE: pid 1, a tid
    per node with a thread_name metadata event, an X event per stage
    plus a `consensus.height` envelope per (node, height)."""
    nodes = timeline.get("nodes", [])
    tid_of = {node: i + 1 for i, node in enumerate(nodes)}
    events = []
    for row in timeline.get("heights", ()):
        for node, cell in row.get("nodes", {}).items():
            tid = tid_of.setdefault(node, len(tid_of) + 1)
            t = cell["t_start"]
            args = {"height": row["height"], "round": cell["round"],
                    "node": node}
            events.append({
                "name": "consensus.height", "ph": tracing.PH_SPAN,
                "pid": 1, "tid": tid, "cat": tracing.CAT_CONSENSUS,
                "ts": t * 1e6, "dur": cell["wall_s"] * 1e6,
                "args": {**args,
                         "verify_wait_s": round(cell["verify_wait_s"], 6)}})
            for stage in STAGES:
                dur = cell["stages"][stage]
                events.append({
                    "name": "consensus.stage." + stage,
                    "ph": tracing.PH_SPAN, "pid": 1, "tid": tid,
                    "cat": tracing.CAT_CONSENSUS,
                    "ts": t * 1e6, "dur": dur * 1e6,
                    "args": {**args, "stage": stage}})
                t += dur
    for node, tid in sorted(tid_of.items(), key=lambda kv: kv[1]):
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": tid, "args": {"name": node}})
    events.append({"name": "process_name", "ph": "M", "pid": 1,
                   "args": {"name": "consensus-timeline"}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"schema": timeline.get("schema",
                                                 TIMELINE_SCHEMA),
                          "nodes": nodes,
                          "height_range": timeline.get("height_range")}}
